"""CodeLlama (LLaMA-architecture) in Flax, designed for GSPMD sharding.

Replaces the reference's HF ``AutoModelForSequenceClassification`` /
``LlamaForCausalLM`` usage (``MSIVD/msivd/train.py:871-885``,
``hf_inference.py:86-107``). Key differences, all TPU-motivated:

- **bf16 + sharding instead of 4-bit NF4**: the reference quantizes to fit
  consumer GPUs (``train.py:873-877``); on TPU the memory math is solved by
  sharding weights over ``tp``/``fsdp`` mesh axes, which XLA turns into
  all-gather/reduce-scatter over ICI. Params carry *logical* axis names
  (``nn.with_logical_partitioning``); :func:`mesh_shardings` maps them onto a
  mesh via :data:`LOGICAL_RULES`.
- **ring attention for long sequences**: ``attn_impl="ring"`` shards the
  sequence over ``sp`` (see ``deepdfa_tpu/ops/ring_attention.py``); the
  reference truncates at ``block_size <= 2048`` (``train.py:199-207``), which
  remains the parity mode (``attn_impl="full"``).
- **no data-dependent control flow**: static shapes, causal mask built from
  ``arange`` comparisons, generation via a fixed-size KV cache — everything
  jits once.

Param tree mirrors HF naming (``model.layers.{i}.self_attn.q_proj`` etc.) so
checkpoint conversion (``deepdfa_tpu/llm/convert.py``) is a transpose-only
rename, no surgery.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepdfa_tpu.ops.ring_attention import full_attention, ring_attention_sharded

__all__ = [
    "LlamaConfig",
    "LlamaModel",
    "LlamaForCausalLM",
    "LOGICAL_RULES",
    "mesh_shardings",
    "codellama_7b",
    "codellama_13b",
    "tiny_llama",
]

# logical param/activation axis -> mesh axis. None = replicated.
LOGICAL_RULES = (
    ("batch", "dp"),
    ("seq", "sp"),
    ("embed", "fsdp"),
    ("heads", "tp"),
    ("kv_heads", "tp"),
    ("mlp", "tp"),
    ("vocab", "tp"),
    ("norm", None),
)


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    """Architecture hyperparameters (HF ``LlamaConfig`` field parity where the
    names overlap, so conversion can read an HF ``config.json`` directly)."""

    vocab_size: int = 32016
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    rope_theta: float = 1_000_000.0  # CodeLlama uses 1e6 (vs LLaMA-2's 1e4)
    rms_norm_eps: float = 1e-5
    max_position_embeddings: int = 16384
    dtype: str = "bfloat16"
    attn_impl: str = "full"  # "full" | "ring"
    remat: bool = False  # rematerialize each decoder layer (memory <-> FLOPs)
    lora_rank: int = 0  # 0 = disabled; >0 adds LoRA to q_proj/v_proj
    lora_alpha: float = 16.0
    # int8-resident projection weights via the fused dequant-matmul pallas
    # kernel (ops/int8_matmul.py): halves weight HBM so 7B fits one v5e.
    # Single-chip inference path — incompatible with a GSPMD mesh.
    int8_runtime: bool = False

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @classmethod
    def from_hf_dict(cls, d: dict) -> "LlamaConfig":
        names = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in names})


def codellama_7b(**kw) -> LlamaConfig:
    """codellama/CodeLlama-7b-* shapes (``train.py`` preset #1)."""
    return LlamaConfig(**kw)


def codellama_13b(**kw) -> LlamaConfig:
    """codellama/CodeLlama-13b-* shapes (presets #2-#5)."""
    return LlamaConfig(
        hidden_size=5120,
        intermediate_size=13824,
        num_hidden_layers=40,
        num_attention_heads=40,
        num_key_value_heads=40,
        **kw,
    )


def tiny_llama(**kw) -> LlamaConfig:
    """Test-size config (CI / dryrun)."""
    defaults = dict(
        vocab_size=320,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=256,
        dtype="float32",
    )
    defaults.update(kw)
    return LlamaConfig(**defaults)


class Int8Dense(nn.Module):
    """Inference-only projection with **int8-resident** weights: the fused
    dequant-matmul pallas kernel (``ops/int8_matmul.py``) reads ``q`` (int8)
    and the per-channel ``scale`` straight from HBM and dequantises tiles in
    VMEM — weight footprint and traffic halve vs bf16. Params are produced
    from a trained checkpoint by ``quant.to_int8_runtime_params``; ``init``
    only fixes shapes. Single-chip path (a pallas call is not GSPMD-
    partitionable here); the mesh path stays bf16."""

    features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        from deepdfa_tpu.ops.int8_matmul import int8_matmul

        q = self.param(
            "q", nn.initializers.zeros_init(), (x.shape[-1], self.features), jnp.int8
        )
        scale = self.param(
            "scale", nn.initializers.ones_init(), (self.features,), jnp.float32
        )
        return int8_matmul(
            x, q, scale,
            out_dtype=jnp.dtype(self.dtype),
            interpret=jax.default_backend() == "cpu",
        )


def _dense(
    features: int, in_axis: str, out_axis: str, dtype, name: str,
    int8: bool = False,
) -> nn.Module:
    if int8:
        return Int8Dense(features, dtype=dtype, name=name)
    return nn.Dense(
        features,
        use_bias=False,
        dtype=dtype,
        kernel_init=nn.with_logical_partitioning(
            nn.initializers.lecun_normal(), (in_axis, out_axis)
        ),
        name=name,
    )


class RMSNorm(nn.Module):
    """LLaMA RMSNorm: fp32 variance, learned scale (HF ``LlamaRMSNorm``)."""

    eps: float
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        w = self.param(
            "weight",
            nn.with_logical_partitioning(nn.initializers.ones, ("norm",)),
            (x.shape[-1],),
        )
        xf = x.astype(jnp.float32)
        var = jnp.mean(xf * xf, axis=-1, keepdims=True)
        y = xf * jax.lax.rsqrt(var + self.eps)
        return (w * y.astype(self.dtype)).astype(self.dtype)


def rope_cos_sin(
    positions: jnp.ndarray, head_dim: int, theta: float
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Rotary tables for integer ``positions`` [..., s] -> cos/sin [..., s, d/2]."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    angles = positions.astype(jnp.float32)[..., None] * inv_freq
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(
    x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray
) -> jnp.ndarray:
    """HF llama rotary convention: rotate_half over a [d/2, d/2] split.

    x: [b, s, h, d]; cos/sin: [b, s, d/2] (or broadcastable).
    """
    d2 = x.shape[-1] // 2
    x1, x2 = x[..., :d2], x[..., d2:]
    cos = cos[:, :, None, :]
    sin = sin[:, :, None, :]
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return out.astype(x.dtype)


def _flash_attention(q, k, v, attn_mask):
    """Pallas flash-attention path (``attn_impl="flash"``): blockwise
    softmax in VMEM via the stock TPU kernel — the single-chip hot-op
    companion to the ``sp``-sharded ring path (TPU only; the CPU test mesh
    uses "full"/"ring"). Layout in: [b, s, h, d]; kernel wants [b, h, s, d].
    Padding rides segment ids: pads get segment 0, real tokens 1, and the
    kernel masks cross-segment attention — same effect as ``kv_mask``."""
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        SegmentIds,
        flash_attention,
    )

    s = q.shape[1]
    if s % 128 != 0:  # kernel block constraint; short/ragged seqs take XLA
        return full_attention(q, k, v, causal=True, kv_mask=attn_mask)
    from deepdfa_tpu.ops.ring_attention import _repeat_kv

    h = q.shape[2]
    k = _repeat_kv(k, h // k.shape[2])
    v = _repeat_kv(v, h // v.shape[2])
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    segment_ids = None
    if attn_mask is not None:
        seg = attn_mask.astype(jnp.int32)
        segment_ids = SegmentIds(q=seg, kv=seg)
    out = flash_attention(
        qt, kt, vt,
        segment_ids=segment_ids,
        causal=True,
        sm_scale=q.shape[-1] ** -0.5,
    )
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


class Attention(nn.Module):
    cfg: LlamaConfig
    mesh: Mesh | None = None

    @nn.compact
    def __call__(
        self,
        x: jnp.ndarray,
        attn_mask: jnp.ndarray | None,
        positions: jnp.ndarray,
        decode: bool = False,
    ) -> jnp.ndarray:
        cfg = self.cfg
        dtype = jnp.dtype(cfg.dtype)
        h, h_kv, d = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
        b, s, _ = x.shape

        q_proj = _dense(h * d, "embed", "heads", dtype, "q_proj", int8=cfg.int8_runtime)
        k_proj = _dense(h_kv * d, "embed", "kv_heads", dtype, "k_proj", int8=cfg.int8_runtime)
        v_proj = _dense(h_kv * d, "embed", "kv_heads", dtype, "v_proj", int8=cfg.int8_runtime)
        o_proj = _dense(cfg.hidden_size, "heads", "embed", dtype, "o_proj", int8=cfg.int8_runtime)

        q = q_proj(x)
        k = k_proj(x)
        v = v_proj(x)
        if cfg.lora_rank > 0:
            from deepdfa_tpu.llm.lora import LoRAAdapter

            q = q + LoRAAdapter(
                h * d, cfg.lora_rank, cfg.lora_alpha, dtype=dtype, name="lora_q"
            )(x)
            v = v + LoRAAdapter(
                h_kv * d, cfg.lora_rank, cfg.lora_alpha, dtype=dtype, name="lora_v"
            )(x)
        q = q.reshape(b, s, h, d)
        k = k.reshape(b, s, h_kv, d)
        v = v.reshape(b, s, h_kv, d)

        cos, sin = rope_cos_sin(positions, d, cfg.rope_theta)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)

        if decode:
            out = self._decode_attend(q, k, v, positions, attn_mask)
        elif cfg.attn_impl == "ring":
            if self.mesh is None:
                raise ValueError("attn_impl='ring' requires a mesh")
            out = ring_attention_sharded(
                q, k, v, self.mesh, causal=True, kv_mask=attn_mask
            )
        elif cfg.attn_impl == "flash":
            out = _flash_attention(q, k, v, attn_mask)
        else:
            out = full_attention(q, k, v, causal=True, kv_mask=attn_mask)
        return o_proj(out.reshape(b, s, h * d))

    def _decode_attend(self, q, k, v, positions, attn_mask):
        """Single-token step against a fixed-size KV cache (autoregressive
        generation; static shapes, index-updated cache). ``attn_mask``
        [b, 1] marks the *current* token's validity — False for left-padding
        (MSIVD pads left with eos, ``train.py:196-208``), and the cached
        validity mask keeps those K/V slots masked for all later steps."""
        cfg = self.cfg
        b = q.shape[0]
        max_len = cfg.max_position_embeddings
        cached_k = self.variable(
            "cache",
            "cached_key",
            jnp.zeros,
            (b, max_len, cfg.num_key_value_heads, cfg.head_dim),
            k.dtype,
        )
        cached_v = self.variable(
            "cache",
            "cached_value",
            jnp.zeros,
            (b, max_len, cfg.num_key_value_heads, cfg.head_dim),
            v.dtype,
        )
        cached_valid = self.variable(
            "cache", "cached_valid", jnp.zeros, (b, max_len), jnp.bool_
        )
        pos = positions[:, 0]  # [b] current absolute position
        idx = pos[0]  # uniform within a batch step
        cached_k.value = jax.lax.dynamic_update_slice(
            cached_k.value, k, (0, idx, 0, 0)
        )
        cached_v.value = jax.lax.dynamic_update_slice(
            cached_v.value, v, (0, idx, 0, 0)
        )
        step_valid = (
            jnp.ones((b, 1), jnp.bool_) if attn_mask is None else attn_mask.astype(bool)
        )
        cached_valid.value = jax.lax.dynamic_update_slice(
            cached_valid.value, step_valid, (0, idx)
        )
        kv_mask = cached_valid.value & (jnp.arange(max_len)[None, :] <= idx)
        return full_attention(
            q,
            cached_k.value,
            cached_v.value,
            causal=False,  # cache mask already enforces causality
            kv_mask=kv_mask,
        )


class MLP(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        cfg = self.cfg
        dtype = jnp.dtype(cfg.dtype)
        gate = _dense(cfg.intermediate_size, "embed", "mlp", dtype, "gate_proj", int8=cfg.int8_runtime)
        up = _dense(cfg.intermediate_size, "embed", "mlp", dtype, "up_proj", int8=cfg.int8_runtime)
        down = _dense(cfg.hidden_size, "mlp", "embed", dtype, "down_proj", int8=cfg.int8_runtime)
        return down(nn.silu(gate(x)) * up(x))


class DecoderLayer(nn.Module):
    cfg: LlamaConfig
    mesh: Mesh | None = None

    @nn.compact
    def __call__(self, x, attn_mask, positions, decode=False):
        cfg = self.cfg
        dtype = jnp.dtype(cfg.dtype)
        h = RMSNorm(cfg.rms_norm_eps, dtype=dtype, name="input_layernorm")(x)
        x = x + Attention(cfg, mesh=self.mesh, name="self_attn")(
            h, attn_mask, positions, decode=decode
        )
        h = RMSNorm(cfg.rms_norm_eps, dtype=dtype, name="post_attention_layernorm")(x)
        x = x + MLP(cfg, name="mlp")(h)
        return nn.with_logical_constraint(x, ("batch", "seq", "embed"))


class LlamaModel(nn.Module):
    """Decoder stack -> final-norm hidden states [b, s, hidden] (the MSIVD
    fusion contract: ``LLMModel.forward`` returns last hidden states,
    ``model.py:42-59``)."""

    cfg: LlamaConfig
    mesh: Mesh | None = None

    @nn.compact
    def __call__(
        self,
        input_ids: jnp.ndarray,
        attn_mask: jnp.ndarray | None = None,
        positions: jnp.ndarray | None = None,
        decode: bool = False,
    ) -> jnp.ndarray:
        cfg = self.cfg
        dtype = jnp.dtype(cfg.dtype)
        if cfg.int8_runtime and self.mesh is not None:
            raise ValueError(
                "int8_runtime is the single-chip inference path — the pallas "
                "dequant-matmul is not GSPMD-partitionable; use bf16 + mesh "
                "sharding for multi-chip"
            )
        if positions is None:
            positions = jnp.broadcast_to(
                jnp.arange(input_ids.shape[1]), input_ids.shape
            )
        embed = nn.Embed(
            cfg.vocab_size,
            cfg.hidden_size,
            dtype=dtype,
            embedding_init=nn.with_logical_partitioning(
                nn.initializers.normal(0.02), ("vocab", "embed")
            ),
            name="embed_tokens",
        )
        x = embed(input_ids)
        x = nn.with_logical_constraint(x, ("batch", "seq", "embed"))
        layer_cls = DecoderLayer
        if cfg.remat:
            layer_cls = nn.remat(DecoderLayer, static_argnums=(4,))
        for i in range(cfg.num_hidden_layers):
            x = layer_cls(cfg, mesh=self.mesh, name=f"layers_{i}")(
                x, attn_mask, positions, decode
            )
        return RMSNorm(cfg.rms_norm_eps, dtype=dtype, name="norm")(x)


class LlamaForCausalLM(nn.Module):
    """LM head on top (generation utility, parity with the reference's
    ``hf_inference.py`` batch-generation helper)."""

    cfg: LlamaConfig
    mesh: Mesh | None = None

    @nn.compact
    def __call__(self, input_ids, attn_mask=None, positions=None, decode=False):
        hidden = LlamaModel(self.cfg, mesh=self.mesh, name="model")(
            input_ids, attn_mask, positions, decode
        )
        logits = _dense(
            self.cfg.vocab_size, "embed", "vocab", jnp.dtype(self.cfg.dtype),
            "lm_head", int8=self.cfg.int8_runtime,
        )(hidden)
        return logits.astype(jnp.float32)


def mesh_shardings(
    model: nn.Module, mesh: Mesh, example_args: tuple, rules=LOGICAL_RULES
):
    """(param_shardings, abstract_params): NamedShardings for every param,
    derived from the logical annotations without materialising weights."""
    abstract = jax.eval_shape(
        lambda: model.init(jax.random.key(0), *example_args)
    )
    logical_specs = nn.get_partition_spec(abstract)
    mesh_specs = nn.logical_to_mesh(logical_specs, rules)
    shardings = jax.tree.map(
        lambda spec: NamedSharding(mesh, spec if spec is not None else P()),
        mesh_specs,
        is_leaf=lambda x: isinstance(x, P) or x is None,
    )
    return shardings, abstract
