"""Joint LLM + GGNN training — the MSIVD training loop, rebuilt for TPU.

Covers ``MSIVD/msivd/train.py:211-585`` (``train``/``evaluate``/``test``):

- **frozen LLM forward** feeding final hidden states into the trainable
  fusion model (``train.py:324-331``); only fusion params (GGNN + head) get
  gradients — the LLM params enter the jitted step as a constant input, so no
  backward pass is ever built through the decoder stack (the TPU analogue of
  ``self.encoder.eval()`` + optimizer over ``gnn_model`` params only).
- AdamW with **no-decay param groups** (bias / norm scales,
  ``train.py:242-260``) via an ``optax.masked`` weight-decay mask.
- **cosine schedule with linear warmup**, ``warmup = max_steps // 50``
  (``train.py:238-266``).
- grad clip ``max_grad_norm`` (``:339``) and **gradient accumulation** via
  ``optax.MultiSteps`` (``:335-360``).
- eval cadence: denser during the first epoch (``first_eval_steps=5`` →
  first eval after 1/5 of an epoch), then every 1/``eval_steps`` of an epoch
  (``train.py:37-38,236-238,366-386``).
- per-epoch checkpoint of the fusion params only — the LLM weights are never
  saved (``train.py:389-392``; LoRA adapters checkpoint separately, see
  ``deepdfa_tpu/llm/lora.py``).
- eval/test: threshold ``P(vul) > best_threshold``, classification report
  with macro avg for Big-Vul / weighted otherwise (``train.py:445-459,
  571-585``).

The whole step — LLM forward + fusion forward/backward/update — is ONE
compiled function; batches are static-shape (``TextBatch`` + ``GraphJoin``),
so it compiles once. For sharded LLMs pass ``llm_params`` already placed with
``mesh_shardings`` — GSPMD partitions the step; the fusion params are tiny and
stay replicated.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Any, Callable, Iterable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deepdfa_tpu.data.prefetch import prefetch_to_device
from deepdfa_tpu.llm.dataset import GraphJoin, JoinedBatch, TextExamples, text_batches
from deepdfa_tpu.llm.fusion import FusionModel, fusion_loss
from deepdfa_tpu.llm.llama import LlamaModel
from deepdfa_tpu.train.metrics import classification_report

__all__ = [
    "JointConfig",
    "JointState",
    "weight_decay_mask",
    "cosine_warmup_schedule",
    "eval_points",
    "best_threshold_sweep",
    "make_joint_steps",
    "JointTrainer",
]


def best_threshold_sweep(
    probs: np.ndarray,
    labels: np.ndarray,
    *,
    macro: bool = True,
    grid: Iterable[float] | None = None,
) -> tuple[float, float]:
    """MSIVD's eval-time threshold selection: sweep ``grid`` (default
    0.01..0.99 in 0.01 steps) over F1 of the positive-probability vector
    and return ``(best_threshold, best_f1)``.

    Deterministic by construction: the grid is fixed, the comparison is
    strict, so ties keep the EARLIEST (lowest) threshold — the selected
    value is a pure function of ``(probs, labels, grid)``, which makes the
    cascade band (``serve.cascade.band_lo/hi``, usually straddling this
    threshold) reproducible across re-evaluations of the same checkpoint."""
    probs = np.asarray(probs, np.float64)
    labels = np.asarray(labels)
    ts = (np.round(np.arange(1, 100) / 100.0, 2) if grid is None
          else np.asarray(list(grid), np.float64))
    key = "f1_macro" if macro else "f1_weighted"
    best_t, best_f = float(ts[0]), -1.0
    for t in ts:
        f1 = classification_report(
            probs, labels, macro=macro, threshold=float(t))[key]
        if f1 > best_f:
            best_t, best_f = float(t), float(f1)
    return best_t, best_f


@dataclasses.dataclass(frozen=True)
class JointConfig:
    """Golden values = the reference argparse defaults (``train.py:588-801``)
    and module constants (``train.py:37-38``)."""

    block_size: int = 256
    train_batch_size: int = 4
    eval_batch_size: int = 4
    learning_rate: float = 5e-5
    weight_decay: float = 0.0
    adam_epsilon: float = 1e-8
    max_grad_norm: float = 1.0
    gradient_accumulation_steps: int = 1
    epochs: int = 1
    best_threshold: float = 0.5
    eval_steps: int = 2  # evals per epoch after the first
    first_eval_steps: int = 5  # evals per first epoch
    seed: int = 42
    # "bigvul" → macro avg (imbalanced); anything else → weighted avg
    dataset_style: str = "bigvul"
    use_gnn: bool = True  # False = --no_flowgnn presets
    # LineVul-combined mode (BASELINE config #3): fine-tune the encoder
    # end-to-end (CodeBERT is 125M params — trainable on one chip) while the
    # pretrained GGNN is frozen — the exact mirror of the MSIVD freeze
    # direction (frozen LLM, trained GNN). ``freeze_gnn`` zeroes updates to
    # the ``flowgnn_encoder`` subtree (``main_cli.py:136-145``'s
    # freeze_graph_weights).
    train_llm: bool = False
    # host→device prefetch depth for the join+transfer pipeline (the
    # DataLoader-worker analogue, data/prefetch.py); 0 disables. Default 1
    # (one staged + one in flight): joint graph batches can be dense
    # adjacencies — hundreds of MB each — so deeper queues trade real HBM
    # for overlap that one staged batch already buys
    prefetch: int = 1
    freeze_gnn: bool = False

    @property
    def report_avg(self) -> str:
        return "macro" if "bigvul" in self.dataset_style else "weighted"


class JointState(NamedTuple):
    params: Any  # fusion params (GGNN + head) — the ONLY trained tree
    opt_state: Any
    rng: jax.Array
    step: jnp.ndarray


def weight_decay_mask(params: Any) -> Any:
    """True = apply weight decay. The reference excludes ``bias`` and
    ``LayerNorm.weight`` (``train.py:242-260``); in our Flax trees that is any
    leaf named ``bias`` and any RMSNorm/LayerNorm ``weight``/``scale``."""

    def mask_path(path: tuple, _leaf) -> bool:
        keys = [getattr(p, "key", getattr(p, "name", str(p))) for p in path]
        if keys and keys[-1] in ("bias", "scale"):
            return False
        if keys and keys[-1] == "weight" and any("norm" in str(k).lower() for k in keys[:-1]):
            return False
        return True

    return jax.tree_util.tree_map_with_path(mask_path, params)


def cosine_warmup_schedule(lr: float, warmup_steps: int, total_steps: int):
    """HF ``get_cosine_schedule_with_warmup`` parity: linear 0→lr over
    ``warmup_steps``, cosine lr→0 over the rest."""
    warmup_steps = max(warmup_steps, 1)
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=lr,
        warmup_steps=warmup_steps,
        # decay_steps includes warmup; the cosine segment must be non-empty
        decay_steps=max(total_steps, warmup_steps + 1),
        end_value=0.0,
    )


def gnn_freeze_labels(params: Any) -> Any:
    """"train"/"freeze" label pytree: every leaf under a ``flowgnn_encoder``
    scope is frozen (``freeze_graph_weights`` parity) — works on both the
    bare fusion tree and the combined ``{"fusion", "llm"}`` tree."""

    def lab(path: tuple, _leaf) -> str:
        keys = [str(getattr(p, "key", getattr(p, "name", p))) for p in path]
        return "freeze" if "flowgnn_encoder" in keys else "train"

    return jax.tree_util.tree_map_with_path(lab, params)


def joint_optimizer(cfg: JointConfig, steps_per_epoch: int, params: Any):
    """clip → AdamW(no-decay mask) → cosine-warmup, wrapped in MultiSteps for
    gradient accumulation (micro-step semantics identical to ``train.py``:
    update every ``gradient_accumulation_steps`` batches). With
    ``cfg.freeze_gnn`` the ``flowgnn_encoder`` subtree gets zero updates."""
    opt_steps = (cfg.epochs * steps_per_epoch) // cfg.gradient_accumulation_steps
    warmup = opt_steps // 50  # train.py:238 "args.warmup_steps = max_steps // 50"
    schedule = cosine_warmup_schedule(cfg.learning_rate, warmup, opt_steps)
    tx = optax.chain(
        optax.clip_by_global_norm(cfg.max_grad_norm),
        optax.adamw(
            schedule,
            eps=cfg.adam_epsilon,
            weight_decay=cfg.weight_decay,
            mask=weight_decay_mask(params),
        ),
    )
    if cfg.freeze_gnn:
        tx = optax.multi_transform(
            {"train": tx, "freeze": optax.set_to_zero()},
            gnn_freeze_labels(params),
        )
    if cfg.gradient_accumulation_steps > 1:
        tx = optax.MultiSteps(tx, cfg.gradient_accumulation_steps)
    return tx


def eval_points(steps_per_epoch: int, epoch: int, cfg: JointConfig) -> set[int]:
    """Step indices (within an epoch) after which to run eval. First epoch is
    denser (``first_eval_steps``), later epochs use ``eval_steps``
    (``train.py:236-238,366-386``)."""
    per = cfg.first_eval_steps if epoch == 0 else cfg.eval_steps
    stride = max(steps_per_epoch // per, 1)
    return {s for s in range(stride - 1, steps_per_epoch, stride)}


def make_joint_steps(
    llm: LlamaModel,
    fusion: FusionModel,
    tx: optax.GradientTransformation,
    train_llm: bool = False,
) -> tuple[Callable, Callable]:
    """(train_step, eval_step), both jitted. ``llm_params`` is an input, not a
    capture, so sharded placements propagate and the tree is donated-free.

    ``train_llm=False`` (MSIVD): the LLM forward runs on the constant
    ``llm_params`` input with no backward built through the stack.
    ``train_llm=True`` (LineVul-combined): the trained tree is
    ``{"fusion": ..., "llm": ...}`` and gradients flow through the encoder;
    the ``llm_params`` step argument is ignored (pass ``None``)."""

    def hidden_states(llm_params, batch: JoinedBatch, dropout_rng=None):
        ids = jnp.asarray(batch.text.input_ids)
        # Explicit pad mask from the dataset (TextBatch.pad_mask): pads share
        # the eos id, so value-sniffing can't find them — the reference's
        # ``attention_mask = input_ids.ne(1)`` (model.py:50) masks *bos*
        # instead of pads; we carry the truth from tokenization time. RoPE is
        # relative, so arange positions over a left-padded row preserve all
        # real-token distances (a uniform shift); the RoBERTa encoder builds
        # mask-aware absolute positions itself.
        #
        # ``dropout_rng`` (train_llm steps only): enables the encoder's HF
        # training regularisation — RobertaEncoder reads hidden/attention
        # dropout rates off its config; the frozen Llama path never uses
        # dropout, matching the reference's frozen-LLM forward.
        kwargs = {}
        if dropout_rng is not None and hasattr(llm, "cfg") and hasattr(
            llm.cfg, "hidden_dropout_prob"
        ):
            kwargs = {"deterministic": False, "rngs": {"dropout": dropout_rng}}
        return llm.apply(
            {"params": llm_params}, ids, jnp.asarray(batch.text.pad_mask),
            **kwargs,
        )

    def loss_fn(params, llm_params, batch: JoinedBatch, rng):
        if train_llm:
            fusion_params, llm_params = params["fusion"], params["llm"]
        else:
            fusion_params = params
        rng, enc_rng = jax.random.split(rng)
        hidden = hidden_states(
            llm_params, batch, dropout_rng=enc_rng if train_llm else None
        )
        logits = fusion.apply(
            {"params": fusion_params},
            hidden,
            batch.graphs if fusion.use_gnn else None,
            deterministic=False,
            token_mask=jnp.asarray(batch.text.pad_mask),
            rngs={"dropout": rng},
        )
        labels = jnp.asarray(batch.text.labels)
        mask = jnp.asarray(batch.mask)
        loss, probs = fusion_loss(logits, labels, mask)
        return loss, probs

    @jax.jit
    def train_step(state: JointState, llm_params, batch: JoinedBatch):
        rng, sub = jax.random.split(state.rng)
        (loss, probs), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, llm_params, batch, sub
        )
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return JointState(params, opt_state, rng, state.step + 1), loss, probs

    @jax.jit
    def eval_step(params, llm_params, batch: JoinedBatch):
        if train_llm:
            fusion_params, llm_params = params["fusion"], params["llm"]
        else:
            fusion_params = params
        hidden = hidden_states(llm_params, batch)
        logits = fusion.apply(
            {"params": fusion_params},
            hidden,
            batch.graphs if fusion.use_gnn else None,
            deterministic=True,
            token_mask=jnp.asarray(batch.text.pad_mask),
        )
        labels = jnp.asarray(batch.text.labels)
        mask = jnp.asarray(batch.mask)
        loss, probs = fusion_loss(logits, labels, mask)
        return loss, probs

    return train_step, eval_step


@dataclasses.dataclass
class JointTrainer:
    """The ``train``/``evaluate``/``test`` driver (``train.py:211-585``)."""

    llm: LlamaModel
    llm_params: Any
    fusion: FusionModel
    cfg: JointConfig
    join: GraphJoin | None  # None = no_flowgnn mode
    run_dir: Path | None = None

    def __post_init__(self):
        self._steps: tuple[Callable, Callable] | None = None
        self.num_missing = 0
        self.history: list[dict] = []

    @property
    def _llm_arg(self):
        """The frozen-encoder step argument: in ``train_llm`` mode the
        encoder lives inside ``state.params`` and the argument is unused —
        don't ship a second copy of the weights into every step."""
        return None if self.cfg.train_llm else self.llm_params

    def _joined(self, batch) -> JoinedBatch:
        if self.join is not None:
            return self.join.join(batch)
        return JoinedBatch(text=batch, graphs=None, mask=batch.mask)

    def _build(
        self, steps_per_epoch: int, example: JoinedBatch, params: Any | None = None
    ) -> JointState | None:
        """Build the optimizer + jitted steps. With resumed ``params`` only
        the step machinery is built (no LLM forward / fusion init / optimizer
        state allocation — they'd be thrown away); without, a fresh
        :class:`JointState` is initialised and returned."""
        fresh = params is None
        rng = jax.random.key(self.cfg.seed)
        if fresh:
            rng, init_rng, drop_rng = jax.random.split(rng, 3)
            hidden = self.llm.apply(
                {"params": self.llm_params},
                jnp.asarray(example.text.input_ids),
                jnp.asarray(example.text.pad_mask),
            )
            params = self.fusion.init(
                {"params": init_rng, "dropout": drop_rng},
                hidden,
                example.graphs if self.fusion.use_gnn else None,
                deterministic=True,
                token_mask=jnp.asarray(example.text.pad_mask),
            )["params"]
            if self.cfg.train_llm:
                # LineVul-combined: the encoder joins the trained tree (and
                # its checkpoint — the reference saves fine-tuned CodeBERT)
                params = {"fusion": params, "llm": self.llm_params}
        self.tx = joint_optimizer(self.cfg, steps_per_epoch, params)
        self._steps = make_joint_steps(
            self.llm, self.fusion, self.tx, train_llm=self.cfg.train_llm
        )
        if not fresh:
            return None
        return JointState(params, self.tx.init(params), rng, jnp.zeros((), jnp.int32))

    def train(
        self,
        train_examples: TextExamples,
        eval_examples: TextExamples,
        state: JointState | None = None,
    ) -> JointState:
        cfg = self.cfg
        n_batches = -(-len(train_examples) // cfg.train_batch_size)
        for epoch in range(cfg.epochs):
            batches = text_batches(
                train_examples,
                cfg.train_batch_size,
                shuffle=True,  # RandomSampler (train.py:227)
                seed=cfg.seed + epoch,
            )
            points = eval_points(n_batches, epoch, cfg)
            tr_loss, tr_num = 0.0, 0
            # overlap the host-side graph join + H2D transfer with the
            # running step (the index-join per batch is real host work —
            # the reference hides it in DataLoader workers)
            joined = prefetch_to_device(
                (self._joined(tb) for tb in batches), size=cfg.prefetch
            )
            for step, jb in enumerate(joined):
                if self._steps is None or state is None:
                    built = self._build(
                        n_batches, jb,
                        params=None if state is None else state.params,
                    )
                    state = state if state is not None else built
                train_step, _ = self._steps
                state, loss, _probs = train_step(state, self._llm_arg, jb)
                tr_loss += float(loss)
                tr_num += 1
                if step in points:
                    self.history.append(
                        {"epoch": epoch, "step": step, **self.evaluate(state.params, eval_examples)}
                    )
            self.history.append(
                {"epoch": epoch, "train_loss": tr_loss / max(tr_num, 1)}
            )
            if self.run_dir is not None:
                self.save(state, f"epoch_{epoch}")
        if self.join is not None:
            self.num_missing = self.join.num_missing
        return state

    def _run_eval(
        self, params, examples: TextExamples
    ) -> tuple[float, np.ndarray, np.ndarray]:
        losses, probs_all, labels_all = [], [], []
        for tb in text_batches(examples, self.cfg.eval_batch_size):
            jb = self._joined(tb)
            if self._steps is None:  # standalone eval (test-only runs)
                self._build(1, jb, params=params)
            _, eval_step = self._steps
            loss, probs = eval_step(params, self._llm_arg, jb)
            losses.append(float(loss))
            keep = np.asarray(jb.mask)
            probs_all.append(np.asarray(probs)[keep])
            labels_all.append(np.asarray(tb.labels)[keep])
        return (
            float(np.mean(losses)) if losses else 0.0,
            np.concatenate(probs_all) if probs_all else np.zeros((0, 2)),
            np.concatenate(labels_all) if labels_all else np.zeros(0, np.int32),
        )

    def evaluate(self, params, examples: TextExamples) -> dict[str, float]:
        """``evaluate`` parity (``train.py:396-465``): mean loss + report."""
        loss, probs, labels = self._run_eval(params, examples)
        report = classification_report(
            probs[:, 1] if probs.size else probs.reshape(0),
            labels,
            macro=self.cfg.report_avg == "macro",
            threshold=self.cfg.best_threshold,
        )
        return {"eval_loss": loss, **{f"eval_{k}": v for k, v in report.items()}}

    def test(self, params, examples: TextExamples) -> dict[str, float]:
        """``test`` parity (``train.py:467-585``) minus profiling (that lives
        in ``deepdfa_tpu/train/profiling.py`` and wraps any step fn)."""
        loss, probs, labels = self._run_eval(params, examples)
        report = classification_report(
            probs[:, 1] if probs.size else probs.reshape(0),
            labels,
            macro=self.cfg.report_avg == "macro",
            threshold=self.cfg.best_threshold,
        )
        return {"test_loss": loss, **{f"test_{k}": v for k, v in report.items()}}

    def save(self, state: JointState, name: str) -> Path:
        """Fusion params only (``train.py:389-392`` saves ``gnn_model``'s
        state_dict; the frozen LLM is never written)."""
        import orbax.checkpoint as ocp

        path = (Path(self.run_dir) / name).absolute()
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(path, state.params, force=True)
        ckptr.wait_until_finished()
        return path

    def load(self, template_params: Any, name: str) -> Any:
        import orbax.checkpoint as ocp

        path = (Path(self.run_dir) / name).absolute()
        return ocp.StandardCheckpointer().restore(path, template_params)
