"""Int8 weight quantization for LLM params — the bitsandbytes role, TPU-way.

The reference quantizes CodeLlama to 4-bit NF4 with bitsandbytes (CUDA
kernels, ``MSIVD/msivd/train.py:873-885``) because consumer GPUs can't hold
bf16 weights. On TPU the *compute* answer is bf16 + sharding (``llama.py``);
what remains useful from quantization is the **memory/storage** story:
per-channel symmetric int8 halves checkpoint size and host RAM vs bf16 (4×
vs fp32) for inference-only deployments. These are pure tree transforms —
quantize once, dequantize to bf16 at load (XLA then runs the usual matmuls;
no custom kernels, no accuracy cliff like NF4).

Only 2-D matmul kernels quantize (embeddings/norms/biases stay exact): the
error there is ~0.3% relative per channel, which for classification heads
and LoRA-adapted decoders is noise — verified in ``tests/test_quant.py``.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["QuantizedLeaf", "quantize_tree", "dequantize_tree", "to_int8_runtime_params"]


class QuantizedLeaf(NamedTuple):
    """Per-output-channel symmetric int8: ``w ≈ q * scale``."""

    q: jnp.ndarray  # int8, same shape as the original kernel
    scale: jnp.ndarray  # float32 [out_channels]

    @property
    def nbytes(self) -> int:
        return int(self.q.size + self.scale.size * 4)


def _quantize(w: jnp.ndarray) -> QuantizedLeaf:
    wf = jnp.asarray(w, jnp.float32)
    absmax = jnp.max(jnp.abs(wf), axis=0)  # per output column
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return QuantizedLeaf(q=q, scale=scale.astype(jnp.float32))


def _should_quantize(path: tuple, leaf: Any) -> bool:
    if not hasattr(leaf, "ndim") or leaf.ndim != 2:
        return False
    last = getattr(path[-1], "key", str(path[-1]))
    return last == "kernel"


def quantize_tree(params: Any) -> Any:
    """Replace every 2-D ``kernel`` with a :class:`QuantizedLeaf`."""
    return jax.tree_util.tree_map_with_path(
        lambda p, v: _quantize(v) if _should_quantize(p, v) else v, params
    )


def dequantize_tree(params: Any, dtype: Any = jnp.bfloat16) -> Any:
    """Materialise compute-ready weights (bf16 by default)."""

    def deq(leaf):
        if isinstance(leaf, QuantizedLeaf):
            return (leaf.q.astype(jnp.float32) * leaf.scale).astype(dtype)
        return leaf

    return jax.tree.map(deq, params, is_leaf=lambda x: isinstance(x, QuantizedLeaf))


def to_int8_runtime_params(params: Any) -> Any:
    """Trained checkpoint tree → ``Int8Dense`` runtime tree: every mapping
    holding a 2-D ``kernel`` (a projection; this model family uses
    ``use_bias=False``) becomes ``{"q": int8, "scale": f32[out]}`` in place,
    matching the params :class:`deepdfa_tpu.llm.llama.Int8Dense` declares.
    Embeddings, norms and LoRA adapters pass through unchanged (they are a
    rounding error of total bytes and precision-sensitive)."""

    from collections.abc import Mapping

    from flax import linen as nn

    # strip logical-partitioning metadata boxes: the int8 runtime is the
    # single-chip path, and a boxed kernel hides its .ndim from the walk
    params = nn.meta.unbox(params)

    def walk(node):
        if isinstance(node, Mapping):  # dict or flax FrozenDict alike
            if "kernel" in node and getattr(node["kernel"], "ndim", 0) == 2:
                leaf = _quantize(node["kernel"])
                out = {k: walk(v) for k, v in node.items() if k != "kernel"}
                out["q"], out["scale"] = leaf.q, leaf.scale
                return out
            return {k: walk(v) for k, v in node.items()}
        return node

    return walk(params)


def tree_nbytes(params: Any) -> int:
    """Total parameter bytes (QuantizedLeaf-aware) — for memory accounting."""
    total = 0
    for leaf in jax.tree.leaves(
        params, is_leaf=lambda x: isinstance(x, QuantizedLeaf)
    ):
        if isinstance(leaf, QuantizedLeaf):
            total += leaf.nbytes
        else:
            total += int(np.asarray(leaf).nbytes)
    return total


def randomize_int8_runtime_params(params: Any, seed: int) -> Any:
    """Value-randomise an int8-runtime param tree (for benchmarking:
    ``Int8Dense.init`` zeroes q/scale, and zero weights give zero logits /
    degenerate losses). int8 leaves go uniform in [-127, 127], per-channel
    scales ~N(1, 0.1)*1e-2, float embeddings ~N(0, 0.02); RMSNorm weights
    (path contains "norm") KEEP their ones-init — randomising them would
    suppress every residual branch ~50x. Leaf-by-leaf on device, never an
    f32 copy of the weights; ``None`` leaves (split LoRA/base trees) pass
    through. Shared by ``bench_llm.py`` and ``scripts/bench_int8_llm.py`` so
    the two int8 benches measure identically-initialised models."""
    import jax

    is_none = lambda v: v is None
    leaves = jax.tree_util.tree_leaves_with_path(params, is_leaf=is_none)
    keys = jax.random.split(jax.random.key(seed), max(len(leaves), 1))

    def fresh(path, leaf, key):
        if leaf is None:
            return None
        if leaf.dtype == jnp.int8:
            return jax.random.randint(
                key, leaf.shape, -127, 128, jnp.int32
            ).astype(jnp.int8)
        name = jax.tree_util.keystr(path)
        if "scale" in name:
            return (1.0 + 0.1 * jax.random.normal(key, leaf.shape, jnp.float32)) * 1e-2
        if "norm" in name.lower():
            return leaf
        return (0.02 * jax.random.normal(key, leaf.shape, jnp.float32)).astype(leaf.dtype)

    flat = [fresh(p, v, k) for (p, v), k in zip(leaves, keys)]
    treedef = jax.tree_util.tree_structure(params, is_leaf=is_none)
    return jax.tree_util.tree_unflatten(treedef, flat)
