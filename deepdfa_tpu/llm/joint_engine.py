"""Tier-2 scoring engine: the joint LLM+GNN model, packaged for serving.

``llm/joint.py`` trains the MSIVD fusion head (frozen LLM hidden states +
GGNN embedding) and checkpoints the fusion params per epoch; this module is
the *serving* half — restore the newest ``epoch_N`` fusion checkpoint from a
``train_joint.py`` run dir and rescore borderline functions through the fused
head. The cascade (``serve/cascade.py``) escalates tier-1 borderline scores
here; ``JointEngine.score`` is the whole contract:

- input: ``[(source_text, Graph), ...]`` — the request's raw source (the LLM
  branch tokenizes it) paired with the already-encoded CPG graph (the GGNN
  branch; ``None`` with ``use_gnn=False``);
- output: ``P(vulnerable)`` per item, computed by the *same jitted
  ``eval_step``* the trainer evaluates with (``make_joint_steps``), so a
  restored checkpoint scores bit-identically to its training-eval pass;
- static shapes: every chunk pads to ``max_batch`` text rows and a fixed
  ``(max_nodes, max_edges)`` graph budget, so the step compiles once.

Two construction paths, mirroring ``scripts/train_joint.py``:

- :meth:`from_run_dir` **hermetic** (default): ``tiny_llama`` +
  :class:`HashTokenizer` — no downloaded weights, the tests/smoke path;
- :meth:`from_run_dir` **sharded**: pass ``hf_checkpoint=`` (+ ``mesh=``) to
  load CodeLlama through ``llm/llama.py``'s converter and tp/fsdp placement
  (``mesh_shardings``); the fusion tree is tiny and stays replicated.
"""

from __future__ import annotations

import threading
from pathlib import Path
from typing import Any, Sequence

import numpy as np

__all__ = ["JointEngine", "newest_epoch_dir"]


def _placeholder_graph(n_nodes: int = 1):
    """A minimal graph carrying the full feature schema real extractions emit
    (`_ABS_DATAFLOW` combined-vocab + one column per subkey) — enough to trace
    fusion.init / warm the compiled program under any ``concat_all_absdf``
    setting."""
    from deepdfa_tpu.config import ALL_SUBKEYS
    from deepdfa_tpu.data.graphs import Graph

    feats = {f"_ABS_DATAFLOW_{sk}": np.zeros(n_nodes, np.int32) for sk in ALL_SUBKEYS}
    feats["_ABS_DATAFLOW"] = np.zeros(n_nodes, np.int32)
    return Graph(
        senders=np.zeros(0, np.int32),
        receivers=np.zeros(0, np.int32),
        node_feats=feats,
        gid=0,
    )


def newest_epoch_dir(run_dir: str | Path) -> Path | None:
    """Newest ``epoch_N`` checkpoint under a ``train_joint.py`` run dir
    (numeric sort — ``epoch_10`` beats ``epoch_9``), or None."""
    epochs = sorted(
        Path(run_dir).glob("epoch_*"),
        key=lambda p: int(p.name.split("_")[1]),
    )
    return epochs[-1] if epochs else None


class JointEngine:
    """Joint-model rescorer over a restored fusion checkpoint.

    Thread-safe: the cascade dispatcher is a single thread, but scans may
    share an engine across workers — ``score`` serialises on one lock (the
    jitted forward is the whole cost; contention is not the bottleneck).
    """

    def __init__(
        self,
        llm,
        llm_params,
        fusion,
        fusion_params,
        tokenizer,
        jcfg,
        *,
        max_batch: int = 4,
        max_nodes: int = 4096,
        max_edges: int = 8192,
    ):
        from deepdfa_tpu.llm.joint import make_joint_steps
        from deepdfa_tpu.serve.engine import _params_content_hash

        self.llm = llm
        self.llm_params = llm_params
        self.fusion = fusion
        self.fusion_params = fusion_params
        self.tokenizer = tokenizer
        self.cfg = jcfg
        self.max_batch = int(max_batch)
        self.max_nodes = int(max_nodes)
        self.max_edges = int(max_edges)
        # same rev scheme as tier 1 (ScoringEngine): content hash of the
        # trained tree — the drift sentinel and /metrics key on it
        self.model_rev = _params_content_hash(fusion_params)
        # the trainer's own jitted eval_step — restore→rescore parity is
        # definitional, not best-effort (tx is train-step-only; None is safe)
        _, self._eval_step = make_joint_steps(llm, fusion, None, train_llm=False)
        self._lock = threading.Lock()

    # ------------------------------------------------------------------ build

    @classmethod
    def from_run_dir(
        cls,
        run_dir: str | Path,
        *,
        jcfg=None,
        gnn_cfg=None,
        input_dim: int | None = None,
        vocab_size: int = 2048,
        use_gnn: bool = True,
        max_batch: int = 4,
        max_nodes: int = 4096,
        max_edges: int = 8192,
        hf_checkpoint: str | None = None,
        mesh=None,
    ) -> "JointEngine":
        """Restore the newest ``epoch_N`` fusion checkpoint from a
        ``train_joint.py`` run dir.

        Default is the hermetic pairing ``train_joint.py`` trains with when
        no preset/HF checkpoint is given (``tiny_llama(vocab_size=2048)`` +
        :class:`HashTokenizer`); ``hf_checkpoint`` switches to the real
        CodeLlama stack, placed over ``mesh`` when given.
        """
        import jax
        import orbax.checkpoint as ocp

        from deepdfa_tpu.config import FeatureConfig, GGNNConfig
        from deepdfa_tpu.llm.dataset import HashTokenizer
        from deepdfa_tpu.llm.fusion import FusionModel
        from deepdfa_tpu.llm.joint import JointConfig
        from deepdfa_tpu.llm.llama import LlamaModel, tiny_llama

        jcfg = jcfg or JointConfig()
        if hf_checkpoint is not None:
            from transformers import AutoTokenizer

            from deepdfa_tpu.llm.convert import load_hf_checkpoint, load_hf_config
            from deepdfa_tpu.llm.llama import mesh_shardings

            llm_cfg = load_hf_config(hf_checkpoint)
            tokenizer = AutoTokenizer.from_pretrained(hf_checkpoint)
            llm = LlamaModel(llm_cfg, mesh=mesh)
            llm_params = load_hf_checkpoint(hf_checkpoint)["model"]
            if mesh is not None:
                shardings = mesh_shardings(llm, llm_params, mesh)
                llm_params = jax.device_put(llm_params, shardings)
        else:
            llm_cfg = tiny_llama(vocab_size=vocab_size)
            tokenizer = HashTokenizer(vocab_size=llm_cfg.vocab_size)
            llm = LlamaModel(llm_cfg)
            llm_params = llm.init(
                jax.random.key(0), np.zeros((2, jcfg.block_size), np.int32)
            )["params"]

        fusion = FusionModel(
            gnn_cfg=gnn_cfg or GGNNConfig(),
            input_dim=input_dim if input_dim is not None else FeatureConfig().input_dim,
            llm_hidden_size=llm_cfg.hidden_size,
            use_gnn=use_gnn,
            dropout_rate=0.1,
            pool="last",
        )

        newest = newest_epoch_dir(run_dir)
        if newest is None:
            raise FileNotFoundError(
                f"no epoch_* fusion checkpoint under {run_dir} — run "
                "scripts/train_joint.py --do_train first"
            )
        template = cls._template_params(llm, llm_params, fusion, jcfg, max_nodes, max_edges)
        fusion_params = ocp.StandardCheckpointer().restore(
            newest.absolute(), template
        )
        return cls(
            llm, llm_params, fusion, fusion_params, tokenizer, jcfg,
            max_batch=max_batch, max_nodes=max_nodes, max_edges=max_edges,
        )

    @staticmethod
    def _template_params(llm, llm_params, fusion, jcfg, max_nodes, max_edges):
        """A fusion param tree of the right shape for the orbax restore —
        traced from one placeholder batch (the ``_restore_newest_epoch``
        idiom in ``scripts/train_joint.py``)."""
        import jax
        import jax.numpy as jnp

        from deepdfa_tpu.data.graphs import batch_np

        ids = np.zeros((1, jcfg.block_size), np.int32)
        pad_mask = np.ones((1, jcfg.block_size), bool)
        hidden = llm.apply({"params": llm_params}, jnp.asarray(ids),
                           jnp.asarray(pad_mask))
        graphs = None
        if fusion.use_gnn:
            graphs = batch_np([_placeholder_graph()], 2, max_nodes, max_edges)
        init_rng, drop_rng = jax.random.split(jax.random.key(0))
        return fusion.init(
            {"params": init_rng, "dropout": drop_rng},
            hidden,
            graphs,
            deterministic=True,
            token_mask=jnp.asarray(pad_mask),
        )["params"]

    # ------------------------------------------------------------------ score

    def score(self, items: Sequence[tuple[str, Any]]) -> np.ndarray:
        """``P(vulnerable)`` per ``(source_text, graph)`` item, chunked to
        ``max_batch`` so the jitted step never re-specialises."""
        out = np.zeros(len(items), np.float64)
        with self._lock:
            for start in range(0, len(items), self.max_batch):
                chunk = items[start : start + self.max_batch]
                out[start : start + len(chunk)] = self._score_chunk(chunk)
        return out

    def _score_chunk(self, chunk: Sequence[tuple[str, Any]]) -> np.ndarray:
        from deepdfa_tpu.llm.dataset import (
            GraphJoin,
            JoinedBatch,
            encode_functions,
            text_batches,
        )

        n = len(chunk)
        examples = encode_functions(
            [text for text, _ in chunk],
            [0] * n,  # labels are loss-only; score reads probs
            self.tokenizer,
            self.cfg.block_size,
        )
        tb = next(text_batches(examples, self.max_batch))
        if self.fusion.use_gnn:
            join = GraphJoin(
                graphs={i: g for i, (_, g) in enumerate(chunk) if g is not None},
                max_nodes=self.max_nodes,
                max_edges=self.max_edges,
            )
            jb = join.join(tb)
        else:
            jb = JoinedBatch(text=tb, graphs=None, mask=tb.mask)
        _loss, probs = self._eval_step(self.fusion_params, self.llm_params, jb)
        return np.asarray(probs)[:n, 1].astype(np.float64)

    # ----------------------------------------------------------------- warmup

    def warmup(self) -> dict:
        """Compile the one (max_batch, block, graph-budget) program before
        traffic — a cascade must not pay XLA compile on its first borderline
        request."""
        g = _placeholder_graph() if self.fusion.use_gnn else None
        self.score([("int main() { return 0; }", g)])
        return {"max_batch": self.max_batch, "model_rev": self.model_rev}

    def describe(self) -> dict:
        return {
            "model_rev": self.model_rev,
            "max_batch": self.max_batch,
            "block_size": self.cfg.block_size,
            "use_gnn": bool(self.fusion.use_gnn),
        }
