"""LLM layer: TPU-native replacement of the reference's MSIVD subsystem
(``MSIVD/msivd/`` — CodeLlama + DDFA-GGNN fusion for vulnerability detection).

Where the reference leans on CUDA-only machinery — bitsandbytes 4-bit NF4
quantization (``train.py:873-885``), HF accelerate ``device_map`` layer
placement (``train.py:883``), ``torch.nn.DataParallel`` (``train.py:936``) —
this package uses bf16 weights GSPMD-sharded over a named mesh (tp/fsdp for
weights, dp for batch, sp + ring attention for long sequences).
"""

from deepdfa_tpu.llm.llama import (  # noqa: F401
    LlamaConfig,
    LlamaModel,
    LlamaForCausalLM,
)

__all__ = [
    "LlamaConfig",
    "LlamaModel",
    "LlamaForCausalLM",
    # submodules (imported lazily by callers):
    # convert  — HF checkpoint conversion
    # lora     — adapters, mask/split/merge
    # finetune — LoRA causal-LM tuning stage
    # quant    — int8 weight storage
    # dataset  — text examples + graph index-join
    # fusion   — classification heads over LLM ⊕ GGNN
    # joint    — frozen-LLM joint trainer
    # generate — batch decoding
    # presets  — the five launch configurations
]
