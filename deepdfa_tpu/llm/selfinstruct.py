"""Self-instruct multitask fine-tuning data — the stage that produces the
LoRA checkpoints the fusion trainer consumes (BASELINE config #4).

The reference snapshot only *consumes* these checkpoints
(``MSIVD/msivd/train.py:863-869`` loads ``--finetuned_path`` via peft); the
data-construction stage — MSIVD's multitask self-instruct tuning over
DiverseVul — predates it. This module owns that stage natively:

- **multi-round dialogue format** (the MSIVD multitask recipe): round 1 asks
  for the vulnerability verdict, round 2 for the CWE type, round 3 for an
  explanation — each round is an instruction/response pair, concatenated
  into one causal-LM training sequence per example. Non-vulnerable examples
  carry only round 1 (there is nothing to type or explain).
- **response-only loss masking**: the model is graded on its answers, not on
  re-predicting the prompt — ``loss_mask`` marks response tokens (+ the eos
  that terminates each response); prompts and padding carry zero loss
  weight. The attention mask still covers all real tokens.
- encoding works with both the hermetic :class:`~deepdfa_tpu.llm.dataset.
  HashTokenizer` (``encode_raw``) and HF tokenizers
  (``add_special_tokens=False``), left-padded to ``block_size`` like every
  other text path in the framework.

The DiverseVul reader lives in ``deepdfa_tpu.data.ingest.diversevul``; the
driver is ``scripts/finetune_llm.py``.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Sequence

import numpy as np

__all__ = [
    "DialogueRound",
    "multitask_rounds",
    "encode_dialogue",
    "encode_multitask",
    "LMExamples",
    "FinetunePreset",
    "FINETUNE_PRESETS",
]


@dataclasses.dataclass(frozen=True)
class DialogueRound:
    """``prompt`` is the task instruction — NEVER truncated; ``context`` is
    droppable material (the function body) that shrinks first when the
    dialogue exceeds ``block_size``. Keeping them separate means a long
    function can never silently delete the instruction and change the
    supervised task format (round-4 advisor finding)."""

    prompt: str
    response: str
    context: str = ""


def multitask_rounds(
    code: str, vul: int, cwe: str = "", explanation: str = ""
) -> list[DialogueRound]:
    """The MSIVD multitask dialogue for one function: detection always;
    type/explanation rounds only when the example is vulnerable AND the
    dataset provides them (DiverseVul: ``cwe`` list + commit ``message``)."""
    rounds = [
        DialogueRound(
            prompt=(
                "Is the following C/C++ function vulnerable? "
                "Answer yes or no.\n"
            ),
            context=code + "\n",
            response="yes" if vul else "no",
        )
    ]
    if vul and cwe:
        rounds.append(
            DialogueRound(
                prompt="What is the vulnerability type of the function?\n",
                response=str(cwe),
            )
        )
    if vul and explanation:
        rounds.append(
            DialogueRound(
                prompt="Explain the vulnerability.\n",
                response=str(explanation),
            )
        )
    return rounds


class LMExamples(NamedTuple):
    """Column-major store for causal-LM tuning with response-masked loss."""

    input_ids: np.ndarray  # [n, block_size] int32
    pad_mask: np.ndarray  # [n, block_size] bool — True = real token
    loss_mask: np.ndarray  # [n, block_size] bool — True = graded token
    indices: np.ndarray  # [n] int64 dataset ids

    def __len__(self) -> int:
        return int(self.input_ids.shape[0])


def _raw_ids(tokenizer, text: str) -> list[int]:
    if hasattr(tokenizer, "encode_raw"):  # HashTokenizer
        return tokenizer.encode_raw(text)
    return list(tokenizer(text, add_special_tokens=False)["input_ids"])


def encode_dialogue(
    tokenizer, rounds: Sequence[DialogueRound], block_size: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """One training row: ``bos, p1, c1, r1, eos, p2, r2, eos, ...``
    left-padded to ``block_size``; loss on response+eos tokens only.
    Over-long dialogues shrink CONTEXT segments only (the function body),
    from the tail — the instruction prompts and every response stay whole,
    so truncation can never change the supervised task format (the round-4
    advisor caught the previous front-first prompt cut deleting the
    'Answer yes or no.' instruction for exactly the long examples).
    Tail-cut matches the reference's ``truncation=True`` keep-the-head
    behavior (``MSIVD/msivd/train.py:196-208``). If instructions+responses
    alone exceed the block, the degenerate back-truncation applies, keeping
    every earlier answer whole."""
    bos = getattr(tokenizer, "bos_token_id", None)
    eos = tokenizer.eos_token_id
    # (tokens, graded, shrinkable) segments
    segs: list[tuple[list[int], bool, bool]] = []
    if bos is not None:
        segs.append(([bos], False, False))
    for r in rounds:
        segs.append((_raw_ids(tokenizer, r.prompt), False, False))
        if r.context:
            segs.append((_raw_ids(tokenizer, r.context), False, True))
        segs.append((_raw_ids(tokenizer, r.response) + [eos], True, False))
    overflow = sum(len(s[0]) for s in segs) - block_size
    if overflow > 0:
        for i, (toks, graded, shrink) in enumerate(segs):
            if overflow <= 0:
                break
            if shrink:
                cut = min(len(toks), overflow)
                segs[i] = (toks[: len(toks) - cut], graded, shrink)
                overflow -= cut
    ids = [t for toks, _, _ in segs for t in toks]
    loss = [graded for toks, graded, _ in segs for _ in toks]
    if len(ids) > block_size:  # instructions+responses alone exceed the block
        ids, loss = ids[:block_size], loss[:block_size]
    n = len(ids)
    row = np.full(block_size, eos, np.int32)
    pad = np.zeros(block_size, bool)
    lm = np.zeros(block_size, bool)
    row[block_size - n:] = np.asarray(ids, np.int32)
    pad[block_size - n:] = True
    lm[block_size - n:] = np.asarray(loss, bool)
    return row, pad, lm


def encode_multitask(
    codes: Sequence[str],
    vuls: Sequence[int],
    tokenizer,
    block_size: int,
    cwes: Sequence[str] | None = None,
    explanations: Sequence[str] | None = None,
    indices: Sequence[int] | None = None,
) -> LMExamples:
    cwes = cwes if cwes is not None else [""] * len(codes)
    explanations = explanations if explanations is not None else [""] * len(codes)
    if indices is None:
        indices = np.arange(len(codes))
    rows, pads, lms = [], [], []
    for code, vul, cwe, expl in zip(codes, vuls, cwes, explanations):
        rounds = multitask_rounds(str(code), int(vul), str(cwe or ""), str(expl or ""))
        r, p, l = encode_dialogue(tokenizer, rounds, block_size)
        rows.append(r)
        pads.append(p)
        lms.append(l)
    z = lambda a, dt: np.stack(a) if a else np.zeros((0, block_size), dt)
    return LMExamples(
        input_ids=z(rows, np.int32),
        pad_mask=z(pads, bool),
        loss_mask=z(lms, bool),
        indices=np.asarray(indices, np.int64),
    )


@dataclasses.dataclass(frozen=True)
class FinetunePreset:
    """A config-#4 launch: dataset + LLM shapes + tuning hypers."""

    name: str
    dataset: str  # ingest.ds name
    llm: str  # "codellama_7b" | "codellama_13b" | "tiny"
    lora_rank: int
    block_size: int
    learning_rate: float
    epochs: int
    batch_size: int


FINETUNE_PRESETS: dict[str, FinetunePreset] = {
    p.name: p
    for p in [
        # the MSIVD stage-1 recipe: DiverseVul multitask explanation tuning
        # producing the adapter checkpoint --finetuned_path consumes
        FinetunePreset(
            name="diversevul_multitask",
            dataset="diversevul",
            llm="codellama_13b",
            lora_rank=16,
            block_size=2048,
            learning_rate=1e-4,
            epochs=1,
            batch_size=4,
        ),
        FinetunePreset(
            name="bigvul_multitask",
            dataset="bigvul",
            llm="codellama_7b",
            lora_rank=16,
            block_size=1024,
            learning_rate=1e-4,
            epochs=1,
            batch_size=4,
        ),
    ]
}
