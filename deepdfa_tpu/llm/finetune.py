"""LoRA fine-tuning of the LLM — the stage that produces adapter checkpoints.

The reference *consumes* LoRA-finetuned CodeLlama checkpoints
(``--finetuned_path``, ``MSIVD/msivd/train.py:863-869``; applied via peft,
``hf_inference.py:86-107``) — the stage that creates them (multitask
explanation tuning) predates this snapshot. This module owns that stage
natively:

- causal-LM loss (next-token CE) over the real tokens only (pad-masked);
- ONLY the LoRA adapters train: :func:`deepdfa_tpu.llm.lora.lora_mask` routes
  every other param through ``optax.set_to_zero`` — the optimizer state for
  frozen params is empty, matching peft's memory profile;
- AdamW + linear-warmup cosine schedule + global-norm clip (the same
  schedule family as the joint stage);
- adapters checkpoint alone (``split_lora``) — base weights are never
  written, parity with peft adapter dirs.

The full step jits once (static shapes from ``TextExamples``); with a
sharded base model, pass params placed by ``mesh_shardings`` and GSPMD
partitions the backward pass the same as the forward.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deepdfa_tpu.llm.dataset import TextExamples
from deepdfa_tpu.llm.joint import cosine_warmup_schedule
from deepdfa_tpu.llm.llama import LlamaForCausalLM
from deepdfa_tpu.llm.lora import lora_mask, split_lora

__all__ = ["FinetuneConfig", "FinetuneState", "lora_optimizer", "make_lm_steps", "LoraFinetuner"]


@dataclasses.dataclass(frozen=True)
class FinetuneConfig:
    learning_rate: float = 1e-4
    weight_decay: float = 0.0
    max_grad_norm: float = 1.0
    epochs: int = 1
    batch_size: int = 4
    warmup_frac: float = 0.02  # same // 50 family as the joint stage
    seed: int = 0


class FinetuneState(NamedTuple):
    params: Any  # FULL param tree (base frozen + adapters training)
    opt_state: Any
    rng: jax.Array
    step: jnp.ndarray


def lora_optimizer(
    cfg: FinetuneConfig, params: Any, total_steps: int
) -> optax.GradientTransformation:
    """clip → AdamW on LoRA leaves only; every other leaf is zeroed so the
    base model never moves and its optimizer state is empty."""
    warmup = max(int(total_steps * cfg.warmup_frac), 1)
    schedule = cosine_warmup_schedule(cfg.learning_rate, warmup, total_steps)
    inner = optax.chain(
        optax.clip_by_global_norm(cfg.max_grad_norm),
        optax.adamw(schedule, weight_decay=cfg.weight_decay),
    )
    labels = jax.tree.map(lambda is_lora: "lora" if is_lora else "frozen", lora_mask(params))
    return optax.multi_transform({"lora": inner, "frozen": optax.set_to_zero()}, labels)


def lm_loss(
    logits: jnp.ndarray,  # [b, s, v]
    input_ids: jnp.ndarray,  # [b, s]
    pad_mask: jnp.ndarray,  # [b, s] True = real token
    loss_mask: jnp.ndarray | None = None,  # [b, s] True = graded token
) -> jnp.ndarray:
    """Next-token CE over positions whose *target* is a real token — or,
    with ``loss_mask`` (self-instruct multitask tuning), only positions
    whose target is a *response* token: the model is graded on its answers,
    not on re-predicting the prompt."""
    targets = input_ids[:, 1:]
    w = (pad_mask if loss_mask is None else loss_mask)[:, 1:].astype(jnp.float32)
    ce = optax.softmax_cross_entropy_with_integer_labels(logits[:, :-1], targets)
    return jnp.sum(ce * w) / jnp.maximum(jnp.sum(w), 1.0)


def make_lm_steps(
    model: LlamaForCausalLM, tx: optax.GradientTransformation
) -> tuple[Callable, Callable]:
    """Steps take an optional response-only ``loss_mask`` (None = grade all
    real tokens; attention always sees the full ``pad_mask``)."""

    def loss_fn(params, ids, mask, loss_mask=None):
        logits = model.apply({"params": params}, ids, mask)
        return lm_loss(logits, ids, mask, loss_mask)

    @jax.jit
    def train_step(state: FinetuneState, ids, mask, loss_mask=None):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, ids, mask, loss_mask)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return FinetuneState(params, opt_state, state.rng, state.step + 1), loss

    eval_step = jax.jit(loss_fn)
    return train_step, eval_step


def _lm_batches(examples, batch_size: int, seed: int = 0):
    """Fixed-shape ``(ids, pad_mask, loss_mask|None)`` batches over
    :class:`TextExamples` or :class:`LMExamples`, delegating the static-
    tail-batch contract to :func:`~deepdfa_tpu.llm.dataset.text_batches`
    (one implementation of the no-recompile invariant); ``loss_mask`` rows
    are re-joined by row position and zeroed on padded tail rows."""
    from deepdfa_tpu.llm.dataset import text_batches

    has_lm = hasattr(examples, "loss_mask")
    n = len(examples)
    te = TextExamples(
        input_ids=examples.input_ids,
        labels=np.zeros(n, np.int32),
        indices=np.arange(n),  # row positions, the loss_mask join key
        pad_mask=examples.pad_mask,
    ) if has_lm else examples
    for tb in text_batches(te, batch_size, shuffle=True, seed=seed):
        lm = None
        if has_lm:
            rows = np.clip(tb.indices, 0, None).astype(np.intp)
            lm = examples.loss_mask[rows].copy()
            lm[~tb.mask] = False  # padded tail rows carry zero loss
        yield tb.input_ids, tb.pad_mask, lm


@dataclasses.dataclass
class LoraFinetuner:
    model: LlamaForCausalLM
    cfg: FinetuneConfig
    run_dir: Path | None = None

    def train(self, params: Any, examples) -> tuple[Any, list[float]]:
        """Returns (params with tuned adapters, per-epoch mean losses).

        ``examples`` is :class:`TextExamples` (plain causal-LM, loss on all
        real tokens) or :class:`~deepdfa_tpu.llm.selfinstruct.LMExamples`
        (multitask dialogues, loss on response tokens only)."""
        cfg = self.cfg
        n_batches = -(-len(examples) // cfg.batch_size)
        tx = lora_optimizer(cfg, params, total_steps=cfg.epochs * n_batches)
        train_step, _ = make_lm_steps(self.model, tx)
        state = FinetuneState(
            params, tx.init(params), jax.random.key(cfg.seed), jnp.zeros((), jnp.int32)
        )
        epoch_losses: list[float] = []
        for epoch in range(cfg.epochs):
            losses = []
            for ids, pad, loss_mask in _lm_batches(
                examples, cfg.batch_size, seed=cfg.seed + epoch
            ):
                state, loss = train_step(
                    state, jnp.asarray(ids), jnp.asarray(pad),
                    None if loss_mask is None else jnp.asarray(loss_mask),
                )
                losses.append(float(loss))
            epoch_losses.append(float(np.mean(losses)))
            if self.run_dir is not None:
                self.save_adapters(state.params, f"adapters_epoch_{epoch}")
        return state.params, epoch_losses

    def save_adapters(self, params: Any, name: str) -> Path:
        """Adapters only (peft-dir parity: the base model is never written)."""
        import orbax.checkpoint as ocp

        adapters, _ = split_lora(params)
        path = (Path(self.run_dir) / name).absolute()
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(path, adapters, force=True)
        ckptr.wait_until_finished()
        return path

    def load_adapters(self, params: Any, name: str) -> Any:
        """Graft saved adapters onto a (fresh or base) param tree."""
        import orbax.checkpoint as ocp

        template, _base = split_lora(params)
        path = (Path(self.run_dir) / name).absolute()
        adapters = ocp.StandardCheckpointer().restore(path, template)

        def pick(path, p):
            node = adapters
            for k in path:
                if not isinstance(node, dict) or k.key not in node:
                    return p
                node = node[k.key]
            return p if node is None else node

        return jax.tree_util.tree_map_with_path(pick, params)
