"""RoBERTa-family bidirectional encoder (CodeBERT) in Flax — the LineVul side
of BASELINE config #3 ("DeepDFA + LineVul fused classifier").

The reference's third evaluation config trains LineVul — a CodeBERT
(`microsoft/codebert-base`, RoBERTa-base architecture) sequence classifier —
and then the combination, where DeepDFA's pooled GGNN embedding is
concatenated with the CLS vector before the classification head
(``scripts/performance_evaluation.sh:7-9``; the LineVul tree itself is not
vendored in the reference snapshot, so the contract here is the public
LineVul/CodeBERT architecture plus the reference's freeze-transfer hook,
``DDFA/code_gnn/main_cli.py:136-145``).

TPU design notes (vs a torch translation):

- bidirectional attention is a single masked softmax over the full [s, s]
  score matrix — no causal structure, no KV cache; XLA fuses the mask add
  into the softmax. Sequences are short (LineVul block 512), so no ring/sp
  path is needed; the encoder rides ``dp``/``fsdp``/``tp`` mesh axes via the
  same logical-axis rules as the Llama stack (``llama.py LOGICAL_RULES``).
- learned absolute positions (RoBERTa convention: real tokens get
  consecutive positions starting at ``pad_token_id + 1``) are computed from
  the explicit pad mask, so the framework-wide left-pad convention works
  unchanged — position embeddings see the same values as HF's
  right-padded layout, shifted mask-aware.
- the param tree mirrors HF naming (``embeddings.word_embeddings``,
  ``encoder.layer.{i}.attention.self.query`` → ``layer_{i}/attention/self/
  query``), so :func:`convert_hf_roberta` is a rename/transpose, no surgery.

``RobertaEncoder.apply(params, ids, pad_mask)`` returns final hidden states
``[b, s, h]`` — the same contract as :class:`~deepdfa_tpu.llm.llama.LlamaModel`,
so the joint trainer drives either stack.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "RobertaConfig",
    "RobertaEncoder",
    "codebert_base",
    "tiny_roberta",
    "convert_hf_roberta",
    "roberta_position_ids",
]


@dataclasses.dataclass(frozen=True)
class RobertaConfig:
    """HF ``RobertaConfig`` field parity where names overlap (so an HF
    ``config.json`` loads directly via :meth:`from_hf_dict`)."""

    vocab_size: int = 50265
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 514
    type_vocab_size: int = 1
    layer_norm_eps: float = 1e-5
    pad_token_id: int = 1
    # HF training regularisation (LineVul fine-tunes CodeBERT end-to-end
    # with these at 0.1): applied only when a caller passes
    # ``deterministic=False`` — inference/parity paths are unaffected
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    dtype: str = "float32"  # bfloat16 on TPU; f32 for parity tests

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @classmethod
    def from_hf_dict(cls, d: dict) -> "RobertaConfig":
        names = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in names})


def codebert_base(**kw) -> RobertaConfig:
    """microsoft/codebert-base shapes (RoBERTa-base; the LineVul encoder)."""
    return RobertaConfig(**kw)


def tiny_roberta(**kw) -> RobertaConfig:
    """Test-size config (CI / hermetic demo)."""
    defaults = dict(
        vocab_size=320,
        hidden_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        intermediate_size=128,
        max_position_embeddings=260,
    )
    defaults.update(kw)
    return RobertaConfig(**defaults)


def roberta_position_ids(pad_mask: jnp.ndarray, pad_token_id: int) -> jnp.ndarray:
    """RoBERTa position ids from the pad mask: real tokens count up from
    ``pad_token_id + 1`` in sequence order, pads sit at ``pad_token_id``
    (HF ``create_position_ids_from_input_ids`` semantics, but driven by the
    explicit mask — pad==eos value-sniffing is the bug the dataset layer
    already refuses to replicate)."""
    m = pad_mask.astype(jnp.int32)
    return jnp.cumsum(m, axis=1) * m + pad_token_id


def _dense(features: int, in_axis: str, out_axis: str, dtype, name: str) -> nn.Module:
    return nn.Dense(
        features,
        use_bias=True,
        dtype=dtype,
        kernel_init=nn.with_logical_partitioning(
            nn.initializers.lecun_normal(), (in_axis, out_axis)
        ),
        bias_init=nn.with_logical_partitioning(
            nn.initializers.zeros_init(), (out_axis,)
        ),
        name=name,
    )


def _layer_norm(eps: float) -> nn.LayerNorm:
    """Post-LN LayerNorm in f32 (BERT-family numerics are LN-sensitive);
    named ``LayerNorm`` so the param path mirrors HF exactly."""
    return nn.LayerNorm(
        epsilon=eps, dtype=jnp.float32, param_dtype=jnp.float32, name="LayerNorm"
    )


class _SelfAttention(nn.Module):
    """``attention.self``: Q/K/V projections + bidirectional masked softmax."""

    cfg: RobertaConfig

    @nn.compact
    def __call__(
        self, x: jnp.ndarray, pad_mask: jnp.ndarray | None,
        deterministic: bool = True,
    ) -> jnp.ndarray:
        cfg = self.cfg
        dtype = jnp.dtype(cfg.dtype)
        b, s, _ = x.shape
        h, d = cfg.num_attention_heads, cfg.head_dim
        q = _dense(h * d, "embed", "heads", dtype, "query")(x).reshape(b, s, h, d)
        k = _dense(h * d, "embed", "heads", dtype, "key")(x).reshape(b, s, h, d)
        v = _dense(h * d, "embed", "heads", dtype, "value")(x).reshape(b, s, h, d)
        # [b, h, s_q, s_k] scores in f32; pads masked on the key axis only —
        # pad *query* rows produce garbage that downstream pooling never reads
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
        scores = scores / np.sqrt(d)
        if pad_mask is not None:
            bias = jnp.where(pad_mask[:, None, None, :], 0.0, -1e9)
            scores = scores + bias
        probs = jax.nn.softmax(scores, axis=-1).astype(dtype)
        probs = nn.Dropout(cfg.attention_probs_dropout_prob,
                           deterministic=deterministic)(probs)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        return out.reshape(b, s, h * d)


class _AttentionBlock(nn.Module):
    """``attention``: self-attention + output projection + residual post-LN."""

    cfg: RobertaConfig

    @nn.compact
    def __call__(
        self, x: jnp.ndarray, pad_mask: jnp.ndarray | None,
        deterministic: bool = True,
    ) -> jnp.ndarray:
        attn = _SelfAttention(self.cfg, name="self")(x, pad_mask, deterministic)
        # HF nests output.dense + output.LayerNorm under attention.output —
        # the tree shape is attention/{self,output}/...
        return _AttnOutput(self.cfg, name="output")(attn, x, deterministic)


class _AttnOutput(nn.Module):
    cfg: RobertaConfig

    @nn.compact
    def __call__(
        self, attn: jnp.ndarray, residual: jnp.ndarray,
        deterministic: bool = True,
    ) -> jnp.ndarray:
        cfg = self.cfg
        dtype = jnp.dtype(cfg.dtype)
        y = _dense(cfg.hidden_size, "heads", "embed", dtype, "dense")(attn)
        y = nn.Dropout(cfg.hidden_dropout_prob, deterministic=deterministic)(y)
        return _layer_norm(cfg.layer_norm_eps)(y + residual).astype(dtype)


class _FFNOutput(nn.Module):
    cfg: RobertaConfig

    @nn.compact
    def __call__(
        self, ff: jnp.ndarray, residual: jnp.ndarray,
        deterministic: bool = True,
    ) -> jnp.ndarray:
        cfg = self.cfg
        dtype = jnp.dtype(cfg.dtype)
        y = _dense(cfg.hidden_size, "mlp", "embed", dtype, "dense")(ff)
        y = nn.Dropout(cfg.hidden_dropout_prob, deterministic=deterministic)(y)
        return _layer_norm(cfg.layer_norm_eps)(y + residual).astype(dtype)


class _Intermediate(nn.Module):
    cfg: RobertaConfig

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        cfg = self.cfg
        dtype = jnp.dtype(cfg.dtype)
        y = _dense(cfg.intermediate_size, "embed", "mlp", dtype, "dense")(x)
        # HF "gelu" is the exact (erf) form
        return nn.gelu(y, approximate=False)


class RobertaLayer(nn.Module):
    cfg: RobertaConfig

    @nn.compact
    def __call__(
        self, x: jnp.ndarray, pad_mask: jnp.ndarray | None,
        deterministic: bool = True,
    ) -> jnp.ndarray:
        x = _AttentionBlock(self.cfg, name="attention")(x, pad_mask, deterministic)
        ff = _Intermediate(self.cfg, name="intermediate")(x)
        x = _FFNOutput(self.cfg, name="output")(ff, x, deterministic)
        return nn.with_logical_constraint(x, ("batch", "seq", "embed"))


class _Embeddings(nn.Module):
    cfg: RobertaConfig
    deterministic: bool = True

    @nn.compact
    def __call__(self, input_ids: jnp.ndarray, positions: jnp.ndarray) -> jnp.ndarray:
        cfg = self.cfg
        dtype = jnp.dtype(cfg.dtype)

        def emb(n, name, row_axis="vocab"):
            # only the word table is big enough to shard its rows over tp;
            # position tables can be odd-sized and the token-type table has
            # ONE row (RoBERTa never uses segment B) — those replicate
            return nn.Embed(
                n, cfg.hidden_size, dtype=dtype,
                embedding_init=nn.with_logical_partitioning(
                    nn.initializers.normal(0.02), (row_axis, "embed")
                ),
                name=name,
            )

        x = emb(cfg.vocab_size, "word_embeddings")(input_ids)
        x = x + emb(cfg.max_position_embeddings, "position_embeddings",
                    row_axis=None)(positions)
        # token type 0 everywhere (RoBERTa never uses segment B)
        x = x + emb(cfg.type_vocab_size, "token_type_embeddings",
                    row_axis=None)(jnp.zeros_like(input_ids))
        x = _layer_norm(cfg.layer_norm_eps)(x).astype(dtype)
        return nn.Dropout(cfg.hidden_dropout_prob,
                          deterministic=self.deterministic)(x)


class RobertaEncoder(nn.Module):
    """Embeddings + ``num_hidden_layers`` post-LN blocks → final hidden
    states ``[b, s, h]``. Same apply contract as ``LlamaModel`` so the joint
    trainer and fusion head drive either stack; the CLS read happens in the
    fusion head (``pool="cls"``)."""

    cfg: RobertaConfig

    @nn.compact
    def __call__(
        self,
        input_ids: jnp.ndarray,
        pad_mask: jnp.ndarray | None = None,
        positions: jnp.ndarray | None = None,
        deterministic: bool = True,
    ) -> jnp.ndarray:
        cfg = self.cfg
        if positions is None:
            if pad_mask is None:
                positions = (
                    jnp.broadcast_to(jnp.arange(input_ids.shape[1]), input_ids.shape)
                    + cfg.pad_token_id + 1
                )
            else:
                positions = roberta_position_ids(pad_mask, cfg.pad_token_id)
        x = _Embeddings(cfg, deterministic, name="embeddings")(input_ids, positions)
        x = nn.with_logical_constraint(x, ("batch", "seq", "embed"))
        for i in range(cfg.num_hidden_layers):
            x = RobertaLayer(cfg, name=f"layer_{i}")(x, pad_mask, deterministic)
        return x


# ---------------------------------------------------------------------------
# HF checkpoint conversion (rename/transpose only, like llm/convert.py)


def convert_hf_roberta(state_dict: dict, dtype=np.float32) -> dict:
    """torch/numpy HF RoBERTa/CodeBERT ``state_dict`` → Flax params tree for
    :class:`RobertaEncoder`. Accepts both bare ``RobertaModel`` names and the
    ``roberta.``-prefixed classifier checkpoints (LineVul publishes the
    latter); pooler/classifier/lm_head tensors are skipped (the fusion head
    owns classification)."""
    params: dict = {}

    def assign(path: list[str], value: np.ndarray) -> None:
        node = params
        for key in path[:-1]:
            node = node.setdefault(key, {})
        node[path[-1]] = value

    for name, tensor in state_dict.items():
        arr = np.asarray(
            tensor.detach().cpu().float().numpy()
            if hasattr(tensor, "detach")
            else tensor,
            dtype=np.float32,
        )
        parts = name.split(".")
        if parts[0] == "roberta":
            parts = parts[1:]
        if parts[0] in ("pooler", "classifier", "lm_head", "qa_outputs"):
            continue
        if parts[0] == "embeddings":
            kind = parts[1]
            if kind == "LayerNorm":
                leaf = "scale" if parts[2] == "weight" else "bias"
                assign(["embeddings", "LayerNorm", leaf], arr.astype(dtype))
            elif kind.endswith("_embeddings"):
                assign(["embeddings", kind, "embedding"], arr.astype(dtype))
            continue
        if parts[0] == "encoder" and parts[1] == "layer":
            i, rest = parts[2], parts[3:]
            base = [f"layer_{i}"] + rest[:-2]
            mod, leaf = rest[-2], rest[-1]
            if mod == "LayerNorm":
                assign(base + ["LayerNorm", "scale" if leaf == "weight" else "bias"],
                       arr.astype(dtype))
            elif leaf == "weight":  # torch Linear [out, in] → Flax kernel [in, out]
                assign(base + [mod, "kernel"], arr.T.astype(dtype))
            elif leaf == "bias":
                assign(base + [mod, "bias"], arr.astype(dtype))
            continue
        # buffers (position_ids etc.): recomputed, skip
    return params
