"""HF → Flax checkpoint conversion for LLaMA-family models.

The reference consumes CodeLlama weights straight from HF hub with torch +
bitsandbytes (``MSIVD/msivd/train.py:871-885``). On TPU the weights must land
as a JAX pytree matching ``deepdfa_tpu/llm/llama.py``'s param layout. This
module does the rename/transpose, streaming from either a torch
``state_dict`` (in memory) or a local HF checkpoint dir (``*.safetensors`` /
``pytorch_model*.bin``) — there is no network access in this environment, so
conversion is strictly from local files.

Mapping (HF name -> ours; Dense kernels are ``W.T``):

    model.embed_tokens.weight                    -> model/embed_tokens/embedding
    model.layers.{i}.input_layernorm.weight      -> model/layers_{i}/input_layernorm/weight
    model.layers.{i}.self_attn.{q,k,v,o}_proj    -> model/layers_{i}/self_attn/{q,k,v,o}_proj/kernel (T)
    model.layers.{i}.post_attention_layernorm    -> model/layers_{i}/post_attention_layernorm/weight
    model.layers.{i}.mlp.{gate,up,down}_proj     -> model/layers_{i}/mlp/{gate,up,down}_proj/kernel (T)
    model.norm.weight                            -> model/norm/weight
    lm_head.weight                               -> lm_head/kernel (T)

``LlamaModel`` (no LM head) uses the same tree minus the ``model/`` prefix and
``lm_head`` — pass ``bare=True``.
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np

from deepdfa_tpu.llm.llama import LlamaConfig

__all__ = [
    "convert_state_dict",
    "load_hf_checkpoint",
    "load_hf_config",
    "load_torch_state",
]


def _assign(tree: dict, path: list[str], value: np.ndarray) -> None:
    node = tree
    for key in path[:-1]:
        node = node.setdefault(key, {})
    node[path[-1]] = value


def convert_state_dict(
    state_dict: dict, dtype=np.float32, bare: bool = False
) -> dict:
    """torch/numpy HF llama ``state_dict`` -> Flax params tree.

    ``bare=True`` targets :class:`LlamaModel` (drops the ``model`` wrapper and
    the LM head); otherwise :class:`LlamaForCausalLM`.
    """
    params: dict = {}
    for name, tensor in state_dict.items():
        arr = np.asarray(
            tensor.detach().cpu().float().numpy()
            if hasattr(tensor, "detach")
            else tensor,
            dtype=np.float32,
        )
        parts = name.split(".")
        if parts[-1] == "weight":
            parts = parts[:-1]
        if parts[0] == "model":
            parts = parts[1:]
        prefix = [] if bare else ["model"]
        if parts[0] == "lm_head":
            if bare:
                continue
            _assign(params, ["lm_head", "kernel"], arr.T.astype(dtype))
            continue
        if parts[0] == "embed_tokens":
            _assign(params, prefix + ["embed_tokens", "embedding"], arr.astype(dtype))
            continue
        if parts[0] == "norm":
            _assign(params, prefix + ["norm", "weight"], arr.astype(dtype))
            continue
        if parts[0] == "layers":
            i = parts[1]
            rest = parts[2:]
            base = prefix + [f"layers_{i}"] + rest[:-1] if len(rest) > 1 else prefix + [f"layers_{i}"]
            leaf = rest[-1]
            if leaf.endswith("_proj"):
                _assign(params, base + [leaf, "kernel"], arr.T.astype(dtype))
            elif leaf.endswith("layernorm"):
                _assign(params, base + [leaf, "weight"], arr.astype(dtype))
            else:  # rotary_emb.inv_freq and other buffers: recomputed, skip
                continue
            continue
        # anything else (rotary buffers, score heads we don't use): skip
    return params


def load_hf_config(ckpt_dir: str | Path) -> LlamaConfig:
    with open(Path(ckpt_dir) / "config.json") as f:
        return LlamaConfig.from_hf_dict(json.load(f))


def load_torch_state(ckpt_dir: str | Path) -> dict:
    """Raw HF ``state_dict`` from a local checkpoint dir (safetensors
    preferred, torch .bin fallback; torch imported only when needed).
    Architecture-agnostic — the llama and roberta converters both feed on
    it."""
    ckpt_dir = Path(ckpt_dir)
    state: dict = {}
    st_files = sorted(ckpt_dir.glob("*.safetensors"))
    if st_files:
        from safetensors.numpy import load_file

        for f in st_files:
            state.update(load_file(str(f)))
    else:
        bin_files = sorted(ckpt_dir.glob("pytorch_model*.bin")) or sorted(
            ckpt_dir.glob("*.pt")
        )
        if not bin_files:
            raise FileNotFoundError(f"no weights found under {ckpt_dir}")
        import torch

        for f in bin_files:
            state.update(torch.load(f, map_location="cpu", weights_only=True))
    return state


def load_hf_checkpoint(
    ckpt_dir: str | Path, dtype=np.float32, bare: bool = False
) -> dict:
    """Convert a local HF checkpoint directory (safetensors preferred,
    torch .bin fallback) into a Flax params tree."""
    return convert_state_dict(load_torch_state(ckpt_dir), dtype=dtype, bare=bare)
