"""``deepdfa-tpu scan <repo-or-dir>`` — the streaming end-to-end surface.

``predict`` scores a handful of files with full statement ranking; *scan*
is the corpus-scale sibling: walk every C source under a repo, stream the
files through the work-stealing :class:`~deepdfa_tpu.data.extraction.
ExtractionPool` with the content-addressed :class:`~deepdfa_tpu.data.
extract_cache.ExtractCache` in front, and (when a checkpoint or exported
artifact is given) batch the encoded functions through the serving
:class:`~deepdfa_tpu.serve.engine.ScoringEngine` grouped by serve bucket.

The economics mirror the ingest pipeline, not the request path: a re-scan
of a mostly-unchanged repo re-encodes only changed files (the cache key is
the whitespace-normalized content hash salted with the vocabulary hash, so
a re-vocab invalidates cleanly), an unparseable file is one error row
(never a dead scan), and a poison file lands in quarantine without idling
the other workers.
"""

from __future__ import annotations

import json
import logging
import time
from pathlib import Path
from typing import Sequence

from deepdfa_tpu.data.extract_cache import ExtractCache
from deepdfa_tpu.data.extraction import ExtractionPool
from deepdfa_tpu.pipeline import vocab_content_hash

__all__ = ["scan_paths", "scan_command"]

logger = logging.getLogger("deepdfa_tpu")

C_SUFFIXES = (".c",)  # the frontend is a C11 parser (pycparser) — see predict


def collect_c_files(paths: Sequence[str | Path]) -> list[Path]:
    """Every scannable file under ``paths``: directories recurse over
    ``*.c``; an explicit file path of any extension is honored (the
    caller asked for that exact file). Missing paths raise."""
    out: list[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            out.extend(sorted(p.rglob("*.c")))
        elif p.exists():
            out.append(p)
        else:
            raise FileNotFoundError(p)
    return out


def _session_factory(vocabs, frontend, keep_cpg: bool = False):
    """The scan's encode sessions come from the SAME factory the online
    :class:`~deepdfa_tpu.serve.frontend.FrontendPool` uses — offline and
    online frontends share one pool implementation, so mode (process vs
    thread), the vocab-hash spawn handshake, and timeout semantics
    cannot drift between the two surfaces. ``keep_cpg`` (the interproc
    scan) asks thread sessions to keep the per-function CPGs so the
    supergraph pass reuses them instead of re-parsing every source."""
    from deepdfa_tpu.config import FrontendConfig
    from deepdfa_tpu.serve.frontend import encode_session_factory

    if frontend is None or frontend.mode == "inline":
        # encode must still run on the pool's worker threads — "inline"
        # only means no child processes, i.e. thread-mode sessions
        frontend = FrontendConfig(mode="thread")
    return encode_session_factory(vocabs, frontend, keep_cpg=keep_cpg)


def _score_functions(engine, rows: list[dict], graphs: list) -> None:
    """Batch ``graphs`` through the engine grouped by serve bucket and
    write ``vulnerable_probability`` back onto the paired rows."""
    by_bucket: dict = {}
    for row, g in zip(rows, graphs):
        try:
            bucket = engine.assign_bucket(g)
        except Exception as exc:  # noqa: BLE001 — oversize = error row
            row["error"] = f"{type(exc).__name__}: {exc}"
            continue
        by_bucket.setdefault(engine.bucket_key(bucket), (bucket, []))[1].append(
            (row, g))
    for bucket, pairs in by_bucket.values():
        cap = max(int(bucket.capacity), 1)
        for start in range(0, len(pairs), cap):
            chunk = pairs[start:start + cap]
            probs = engine.score([g for _, g in chunk], bucket)
            for (row, _), p in zip(chunk, probs):
                row["vulnerable_probability"] = round(float(p), 6)


def _cascade_rescore(tier2, band, rows: list[dict], graphs: list,
                     source_by_file: dict[str, str]) -> None:
    """Offline mirror of the serving cascade (``serve/cascade.py``): every
    scored row records the answering ``tier`` and its ``tier1_score``;
    rows inside the borderline band rescore through the tier-2 joint
    engine, fed the owning file's source text (the LLM branch input).
    A tier-2 failure keeps the tier-1 score (invariant 24) and marks the
    borderline rows ``tier2_degraded`` — the scan never aborts on it."""
    lo, hi = band
    scored = [(row, g) for row, g in zip(rows, graphs)
              if "vulnerable_probability" in row]
    for row, _ in scored:
        row["tier"] = 1
        row["tier1_score"] = row["vulnerable_probability"]
    borderline = [(row, g) for row, g in scored
                  if lo <= row["vulnerable_probability"] <= hi]
    if not borderline:
        return
    items = [(source_by_file.get(row["file"], ""), g)
             for row, g in borderline]
    try:
        probs = tier2.score(items)
    except Exception as exc:  # noqa: BLE001 — degrade, never abort the scan
        logger.warning("scan cascade: tier-2 rescore failed (%s: %s) — "
                       "keeping tier-1 scores", type(exc).__name__, exc)
        for row, _ in borderline:
            row["tier2_degraded"] = True
        return
    for (row, _), p in zip(borderline, probs):
        row["tier"] = 2
        row["vulnerable_probability"] = round(float(p), 6)


def _interproc_pass(sources: list[tuple[str, str]],
                    parsed: dict[str, list] | None = None):
    """Whole-unit interprocedural pass over the scanned sources: merge the
    per-file CPGs into ONE graph (so calls resolve across file boundaries
    too), build the call-graph supergraph, and run the cross-function
    taint differential (``cpg.interproc``). Findings are the taint flows a
    per-function scan provably cannot see — the source API is in the
    caller, the sink in the callee.

    ``parsed`` maps a file name to its already-parsed per-function CPGs
    (the scan loop's thread-mode encode keeps them) — those files skip
    the second parse entirely; files not in the map (process-mode encode,
    warm old-generation cache entries, parse failures) fall back to
    :func:`~deepdfa_tpu.cpg.frontend.parse_source`. Per-file failures
    degrade to error rows; this never aborts the scan. Returns
    ``(report, supergraph-or-None)`` so the caller can reuse the
    supergraph for hierarchical unit scoring."""
    from deepdfa_tpu.cpg.frontend import parse_source
    from deepdfa_tpu.cpg.interproc import (
        build_supergraph, cross_function_taint, merge_cpgs)

    parsed = parsed or {}
    cpgs, errors = [], []
    n_files, n_reused = 0, 0
    for name, code in sources:
        pre = parsed.get(name)
        if pre:
            cpgs.extend(pre)
            n_files += 1
            n_reused += 1
            continue
        try:
            cpgs.append(parse_source(code))
            n_files += 1
        except Exception as exc:  # noqa: BLE001 — one error row per file
            errors.append({"file": name, "error": f"{type(exc).__name__}: {exc}"})
    base = {"n_files_parsed": n_files, "n_files_reused": n_reused,
            "errors": errors, "findings": [], "attribution": {},
            "call_edges": 0, "functions": 0}
    if not cpgs:
        return base, None
    merged, _ = merge_cpgs(cpgs)
    try:
        sg = build_supergraph(merged)
        cross = cross_function_taint(sg)
    except Exception as exc:  # noqa: BLE001 — degrade, never abort
        logger.warning("scan --interproc: supergraph pass failed (%s: %s)",
                       type(exc).__name__, exc)
        errors.append({"file": "<merged>",
                       "error": f"{type(exc).__name__}: {exc}"})
        return base, None
    base.update(
        findings=cross["findings"],
        attribution=cross["attribution"],
        call_edges=sg.n_call_edges,
        functions=len(sg.callgraph.methods),
    )
    return base, sg


def _interproc_report(sources: list[tuple[str, str]],
                      parsed: dict[str, list] | None = None) -> dict:
    """:func:`_interproc_pass`'s report alone (the stable surface the
    interproc tests and external callers consume)."""
    report, _ = _interproc_pass(sources, parsed)
    return report


def _function_source(file_source: str, cpg) -> str | None:
    """The line-slice of ``file_source`` covering one function's CPG — the
    content the embedding cache keys on. Slicing per function keeps a
    sibling-function edit from invalidating every entry in the file; a
    CPG without line info returns None (the caller falls back to the
    whole file, still correct, just coarser invalidation)."""
    lines = [n.line for n in cpg.nodes.values()
             if getattr(n, "line", None)]
    if not lines:
        return None
    lo, hi = min(lines), max(lines)
    split = file_source.split("\n")
    return "\n".join(split[max(lo - 1, 0):hi])


def _attach_embedding_cache(engine, vocabs, cache_dir) -> None:
    """Front the engine's hierarchical scorer with a content-addressed
    function-embedding cache under ``{cache_dir}/emb`` — keyed on the
    function source × model revision × vocab content × feature config, so
    a warm rescan of unchanged functions re-dispatches zero level-1
    megabatches. No cache dir (or an engine without a hierarchical path)
    is a clean no-op: scoring still works, just uncached."""
    if cache_dir is None:
        return
    try:
        hier = engine.hier
        if hier.cache is not None:
            return  # caller already attached one (e.g. bench harness)
        from deepdfa_tpu.serve.embcache import FunctionEmbeddingCache
        hier.cache = FunctionEmbeddingCache(
            Path(cache_dir) / "emb",
            model_rev=getattr(engine, "model_rev", "unknown") or "unknown",
            vocab_hash=vocab_content_hash(vocabs),
            feature_salt=",".join(getattr(engine, "feat_keys", ()) or ()),
            dim=hier.out_dim,
        )
    except Exception as exc:  # noqa: BLE001 — cache is an optimisation
        logger.warning("scan --interproc: embedding cache unavailable "
                       "(%s: %s)", type(exc).__name__, exc)


def _score_unit(engine, sg, unit_fns: list) -> dict:
    """One hierarchical ``score_unit`` request over the merged unit —
    level-1 embeddings off the fused megabatch kernels (cache-fronted),
    composed over the call graph (``models/ggnn_hier.py``). Any failure
    degrades to a ``unit_error`` entry; the scan never aborts on it."""
    try:
        return engine.score_unit(unit_fns, sg)
    except Exception as exc:  # noqa: BLE001 — degrade, never abort
        logger.warning("scan --interproc: unit scoring failed (%s: %s)",
                       type(exc).__name__, exc)
        return {"unit_error": f"{type(exc).__name__}: {exc}"}


def scan_paths(
    paths: Sequence[str | Path],
    vocabs,
    *,
    engine=None,
    tier2=None,
    tier2_band: tuple[float, float] = (0.35, 0.65),
    n_workers: int = 4,
    cache_dir: str | Path | None = None,
    attempts_per_item: int = 2,
    frontend=None,
    interproc: bool = False,
) -> dict:
    """Scan ``paths``; returns the report dict (also what ``scan.json``
    records). Per-file failures are error rows; nothing aborts the scan."""
    files = collect_c_files(paths)
    sources: list[tuple[str, str]] = [
        (str(f), f.read_text(errors="replace")) for f in files]
    cache = None
    if cache_dir is not None:
        # salt with the vocabulary content: encoding is vocab-dependent, so
        # a re-vocabed corpus must MISS rather than serve stale encodings
        cache = ExtractCache(cache_dir, salt=vocab_content_hash(vocabs))
    pool = ExtractionPool(
        _session_factory(vocabs, frontend, keep_cpg=interproc),
        n_workers=max(1, min(n_workers, max(len(sources), 1))),
        attempts_per_item=attempts_per_item,
        cache=cache,
        cache_code=lambda code: code,
    )
    t0 = time.perf_counter()
    results = pool.run(
        [(name, code) for name, code in sources],
        lambda session, code: session.encode(code),
    )
    elapsed = time.perf_counter() - t0

    source_by_file = dict(sources)
    rows: list[dict] = []
    score_rows: list[dict] = []
    score_graphs: list = []
    parsed_cpgs: dict[str, list] = {}
    unit_fns: list = []
    for res in results:
        if res.error is not None:
            rows.append({"file": res.key, "error": res.error,
                         "quarantined": res.quarantined})
            continue
        if interproc and res.value and all(
                fn.cpg is not None for fn in res.value):
            # thread-mode encode kept the per-function CPGs — the
            # interproc pass reuses them (no second parse); process-mode
            # results and old-generation cache entries re-parse instead
            parsed_cpgs[res.key] = [fn.cpg for fn in res.value]
        for fn in res.value:
            row = {"file": res.key, "function": fn.name,
                   "cache_hit": res.cache_hit}
            if fn.graph is None:
                row["error"] = fn.error
            else:
                if engine is not None:
                    score_rows.append(row)
                    score_graphs.append(fn.graph)
                if interproc:
                    from deepdfa_tpu.models.ggnn_hier import UnitFunction

                    file_code = source_by_file.get(res.key, "")
                    code = (_function_source(file_code, fn.cpg)
                            if fn.cpg is not None else None)
                    unit_fns.append(UnitFunction(
                        fn.name, code or f"{fn.name}\n{file_code}", fn.graph))
            rows.append(row)
    if engine is not None and score_graphs:
        _score_functions(engine, score_rows, score_graphs)
        if tier2 is not None:
            _cascade_rescore(tier2, tier2_band, score_rows, score_graphs,
                             source_by_file)

    n_err = sum(1 for r in rows if "error" in r)
    report = {
        "results": rows,
        "n_files": len(sources),
        "n_functions": len(rows) - sum(1 for r in rows if "function" not in r),
        "n_scored": sum(1 for r in rows if "vulnerable_probability" in r),
        "n_errors": n_err,
        "elapsed_s": round(elapsed, 3),
        "pool": pool.report(),
        "cache": cache.stats() if cache is not None else None,
    }
    if interproc:
        ip_report, sg = _interproc_pass(sources, parsed_cpgs)
        report["interproc"] = ip_report
        if engine is not None and sg is not None and unit_fns:
            _attach_embedding_cache(engine, vocabs, cache_dir)
            ip_report["unit"] = _score_unit(engine, sg, unit_fns)
    if tier2 is not None:
        report["cascade"] = {
            "band": [float(tier2_band[0]), float(tier2_band[1])],
            "n_tier2": sum(1 for r in rows if r.get("tier") == 2),
            "n_degraded": sum(1 for r in rows if r.get("tier2_degraded")),
            "tier2_model_rev": getattr(tier2, "model_rev", "unknown"),
        }
    logger.info(
        "scan: %d file(s) → %d function(s), %d scored, %d error row(s) "
        "in %.2fs (cache %s)", report["n_files"], report["n_functions"],
        report["n_scored"], n_err, elapsed,
        f"hit_rate={report['cache']['hit_rate']:.2f}" if cache else "off",
    )
    return report


def scan_command(cfg, run_dir: Path, targets: Sequence[str], *,
                 ckpt_dir: Path | None = None, artifact: str | None = None,
                 workers: int = 4, cache_dir: Path | None = None,
                 cascade: bool = False, interproc: bool = False) -> dict:
    """The CLI entry: resolve vocabs from the config's shard dir, build a
    scoring engine when a checkpoint/artifact is given (scan still runs
    encode-only without one), write ``scan.json`` atomically."""
    from deepdfa_tpu import utils
    from deepdfa_tpu.pipeline import load_vocabs
    from deepdfa_tpu.resilience.journal import atomic_write_text

    ccfg = cfg.serve.cascade
    if cascade:
        # fail fast, before shard/vocab resolution touches the filesystem
        if artifact is None and ckpt_dir is None:
            raise ValueError(
                "scan --cascade needs tier-1 scores: pass --ckpt-dir or "
                "--artifact")
        if ccfg.joint_dir is None:
            raise ValueError(
                "scan --cascade needs a tier-2 checkpoint: set "
                "serve.cascade.joint_dir (a train_joint.py run dir)")

    sample_text = "_sample" if cfg.data.sample else ""
    shard_dir = utils.processed_dir() / cfg.data.dsname / f"shards{sample_text}"
    vocabs = load_vocabs(shard_dir)

    engine = None
    if artifact is not None:
        from deepdfa_tpu.serve.engine import ScoringEngine

        engine = ScoringEngine.from_artifact(artifact, vocabs=vocabs)
    elif ckpt_dir is not None:
        from deepdfa_tpu.serve.engine import ScoringEngine

        engine = ScoringEngine.from_checkpoint(cfg, ckpt_dir, vocabs)
    else:
        logger.info("scan: no --ckpt-dir/--artifact — encoding without scores")

    tier2 = None
    if cascade:
        from deepdfa_tpu.llm.joint_engine import JointEngine

        tier2 = JointEngine.from_run_dir(
            ccfg.joint_dir, max_batch=ccfg.tier2_max_batch)

    report = scan_paths(
        targets, vocabs, engine=engine, tier2=tier2,
        tier2_band=(ccfg.band_lo, ccfg.band_hi), n_workers=workers,
        cache_dir=cache_dir if cache_dir is not None
        else run_dir / "extract_cache",
        frontend=cfg.serve.frontend, interproc=interproc)
    atomic_write_text(run_dir / "scan.json", json.dumps(report, indent=2))
    print(json.dumps({k: v for k, v in report.items() if k != "results"},
                     sort_keys=True), flush=True)
    return report
