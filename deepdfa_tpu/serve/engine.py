"""ScoringEngine — warm per-bucket compiled scorers for the online path.

The compiled-shape discipline that rules training rules serving too: XLA
programs are specialized to static shapes, so the engine owns a small
ladder of :class:`~deepdfa_tpu.data.graphs.BucketSpec` budgets (size
classes per *graph*, batch budgets per *bucket*) and keeps one compiled
callable warm per bucket. Requests are routed to the smallest size class
that fits their graph (`assign_bucket`), the batcher packs per class, and
`score` pads + dispatches — after the first `warmup()` no request ever
pays a compile.

Two constructors, one contract:

- :meth:`from_checkpoint` — live model + restored params through
  :func:`deepdfa_tpu.predict.make_scorer` (jit; any bucket ladder);
- :meth:`from_artifact` — a pre-exported StableHLO artifact
  (:mod:`deepdfa_tpu.serving`), whose ONE baked shape becomes the only
  bucket; node-label artifacts are reduced to function scores host-side.

Fleet extensions (the distributed-serving layer):

- ``mesh=`` on :meth:`from_model` replicates the engine across every
  device of a ``dp`` mesh (the :mod:`deepdfa_tpu.parallel.dp` shard-map
  machinery): :meth:`score_groups` stacks up to ``n_replicas`` padded
  batches on a leading device axis and scores them in ONE dispatch, one
  batch per device. The micro-batcher packs across replicas.
- :meth:`warmup` takes a :class:`~deepdfa_tpu.serve.warmstore.WarmStore`:
  a miss compiles as before and EXPORTS the bucket's program
  (StableHLO, content-addressed on vocab hash + model rev + bucket
  shape); a hit loads the serialized program instead of re-tracing —
  a joining replica warms its whole ladder with zero cold compiles.
  ``warmup`` returns a report (hits/misses/compile-seconds-saved) and
  journals it when given a journal.

`score` is where the ``serve.engine_raises`` fault point lives: an
injected (or real) engine failure must surface as a per-request error in
the batcher, never as a dead server. All dispatch entry points serialize
on one engine lock — concurrent ``submit()`` callers in latency mode
must never interleave their donated buffers.
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
import time
import warnings
from pathlib import Path

import numpy as np

from deepdfa_tpu.data.graphs import BucketSpec, Graph, _round_up, batch_np
from deepdfa_tpu.resilience import faults

__all__ = ["OversizeGraphError", "ServeBucket", "serve_buckets",
           "mega_bucket", "ScoringEngine", "PendingScore"]


class OversizeGraphError(ValueError):
    """The function's graph exceeds every serving bucket — a per-request
    413, not a reason to grow the compiled-shape ladder at runtime."""


@dataclasses.dataclass(frozen=True)
class ServeBucket:
    """A size class: graphs with ``n_nodes <= graph_nodes`` (and edges
    within the per-graph share) route here; ``spec`` is the padded batch
    budget the bucket's compiled callable is specialized to."""

    spec: BucketSpec
    graph_nodes: int

    @property
    def capacity(self) -> int:
        """Real-graph slots (one BucketSpec slot is the padding sink)."""
        return self.spec.max_graphs - 1

    def admits(self, g: Graph) -> bool:
        return (g.n_nodes <= self.graph_nodes
                and g.n_edges <= 4 * self.graph_nodes
                and self.spec.fits(1, g.n_nodes, g.n_edges))


def serve_buckets(max_batch: int) -> tuple[ServeBucket, ...]:
    """The default ladder: small CFGs (DeepDFA's regime, ~50 nodes) batch
    ``max_batch``-wide; mid-size functions batch narrower; huge ones go
    one-per-batch. Three compiled shapes total — bounded compile cost,
    bounded padding waste."""
    ladder = ((126, max_batch), (1022, max(1, max_batch // 4)), (4094, 1))
    out = []
    for per_graph, gcap in ladder:
        nn = _round_up(gcap * per_graph + 2)
        out.append(ServeBucket(
            spec=BucketSpec(gcap + 1, nn, 4 * nn), graph_nodes=per_graph))
    return tuple(out)


def mega_bucket(max_batch: int, graph_nodes: int = 1022) -> ServeBucket:
    """The cross-bucket megabatch budget: ONE compiled shape wide enough
    to absorb a whole mixed-size request window (small CFGs *and* mid-size
    functions together), so :meth:`ScoringEngine.score_packed` replaces
    the per-size-class ladder walk with a single dispatch. Node/edge
    budgets cover ``2 * max_batch`` DeepDFA-regime graphs plus one
    ``graph_nodes``-sized straggler — graphs over the budget still route
    through the ladder per class."""
    gcap = 2 * max(1, int(max_batch))
    nn_ = _round_up(gcap * 126 + graph_nodes + 2)
    return ServeBucket(spec=BucketSpec(gcap + 1, nn_, 4 * nn_),
                       graph_nodes=graph_nodes)


def _calibration_graphs(feat_keys, buckets, n_per_bucket: int = 4,
                        seed: int = 0):
    """Synthesized int8-gate inputs when the caller has no realworld
    fixtures handy: a few random graphs per bucket size class (feature ids
    in {0, 1} — valid rows in every embedding table). Deterministic
    (seeded) so the gate verdict is reproducible across engine builds."""
    rng = np.random.default_rng(seed)
    out = []
    for b in buckets:
        cap = min(b.graph_nodes, 48)
        for _ in range(n_per_bucket):
            n = int(rng.integers(max(2, cap // 2), cap + 1))
            feats = {k: rng.integers(0, 2, size=n).astype(np.int32)
                     for k in feat_keys}
            out.append(Graph(
                senders=rng.integers(0, n, size=2 * n).astype(np.int32),
                receivers=rng.integers(0, n, size=2 * n).astype(np.int32),
                node_feats=feats).with_self_loops())
    return out


def _params_content_hash(params) -> str:
    """Model revision: a content address of the full parameter tree
    (structure + dtypes + bytes). Two engines share warm-store keys
    exactly when they serve the same weights."""
    import jax

    leaves, treedef = jax.tree.flatten(params)
    h = hashlib.sha256(str(treedef).encode())
    for leaf in leaves:
        arr = np.asarray(leaf)
        h.update(f"{arr.dtype}{arr.shape}".encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()[:16]


class PendingScore:
    """Handle returned by :meth:`ScoringEngine.submit` — the scores stay
    device-resident (no host sync at dispatch); :meth:`result` is the one
    blocking read."""

    __slots__ = ("_dev", "_n")

    def __init__(self, dev, n: int):
        self._dev = dev
        self._n = n

    def result(self) -> np.ndarray:
        return np.asarray(self._dev, np.float32)[: self._n]


class ScoringEngine:
    """``score(graphs, bucket) -> fn_prob[len(graphs)]`` over a fixed
    bucket ladder. ``score_fn`` maps a padded ``BatchedGraphs`` to
    per-graph probabilities ``[max_graphs]`` (already sigmoid'd).

    ``device_fn`` (optional — the live-model constructors set it): a jitted
    ``device batch -> device probs`` callable whose batch argument is
    DONATED, enabling ``latency_mode`` — :meth:`submit` dispatches without
    any host sync and hands back a :class:`PendingScore`; the input buffers
    are consumed by the dispatch (donation) so a submitted batch is never
    reused host-side. ``precision`` records which weight path the engine
    serves (``f32`` or ``int8``); ``int8_score_delta`` the measured
    calibration-batch gate value when int8 was requested.

    ``stacked_fn`` (mesh-replicated engines): maps a ``[n_replicas, ...]``
    stacked batch pytree to ``[n_replicas, max_graphs]`` probabilities —
    one engine replica per device, one dispatch for the whole stack.
    ``export_fn`` (live single-replica engines): ``bucket -> (bytes,
    export_seconds)`` serializing the bucket's compiled program for the
    warm store. ``model_rev`` is the parameter content hash that keys it.

    Every dispatch path holds the engine lock: the donated-buffer submit
    sequence (pad → upload → launch) is a critical section — two threads
    interleaving it could hand one thread's donated buffers to the
    other's dispatch.
    """

    def __init__(self, score_fn, buckets, label_style: str = "graph",
                 feat_keys=(), vocab_hash: str | None = None,
                 device_fn=None, latency_mode: bool = False,
                 precision: str = "f32",
                 int8_score_delta: float | None = None,
                 stacked_fn=None, n_replicas: int = 1,
                 model_rev: str | None = None, export_fn=None,
                 mega: ServeBucket | None = None, hier_factory=None):
        if not buckets:
            raise ValueError("need at least one serving bucket")
        if score_fn is None and stacked_fn is None:
            raise ValueError("need a score_fn (or a stacked_fn for "
                             "mesh-replicated engines)")
        if n_replicas < 1:
            raise ValueError("n_replicas must be >= 1")
        self._score_fn = score_fn
        self._device_fn = device_fn
        self._stacked_fn = stacked_fn
        self._export_fn = export_fn
        self.n_replicas = int(n_replicas)
        self.model_rev = model_rev
        if latency_mode and device_fn is None:
            warnings.warn(
                "latency_mode requires a jit-safe device_fn (live-model "
                "engines only — StableHLO artifact reductions run host-side); "
                "serving in synchronous mode", stacklevel=2)
            latency_mode = False
        self.latency_mode = latency_mode
        self.precision = precision
        self.int8_score_delta = int8_score_delta
        self.buckets = tuple(sorted(
            buckets, key=lambda b: (b.graph_nodes, b.spec.max_graphs)))
        self.label_style = label_style
        self.feat_keys = tuple(feat_keys)
        self.vocab_hash = vocab_hash
        self.mega_bucket = mega
        # packed-dispatch efficiency of the last score_packed call (the
        # nodes/edges/graphs fractions the /metrics padding gauges track)
        self.last_padding_efficiency: dict[str, float] | None = None
        self.n_dispatches = 0
        self.warm_buckets: list[int] = []
        self.last_warmup_report: dict | None = None
        self._bucket_fns: dict[ServeBucket, object] = {}
        # whole-unit hierarchical scoring (models/ggnn_hier.py): live
        # megabatch-compatible engines get a lazy factory; the scorer is
        # built on first score_unit so ladder-only serving pays nothing
        self._hier_factory = hier_factory
        self._hier = None
        self._lock = threading.RLock()
        # attachment point set by the server: every dispatch records its
        # bucket + real-graph count into the crash flight recorder
        self.flight = None

    def _record_dispatch(self, kind: str, bucket, n_graphs: int) -> None:
        if self.flight is not None:  # record() never raises (invariant 14)
            self.flight.record(kind, bucket=bucket.graph_nodes,
                               n_graphs=n_graphs,
                               dispatch=self.n_dispatches)

    # -- routing ------------------------------------------------------------

    def assign_bucket(self, g: Graph) -> ServeBucket:
        for b in self.buckets:
            if b.admits(g):
                return b
        raise OversizeGraphError(
            f"graph with {g.n_nodes} nodes / {g.n_edges} edges exceeds the "
            f"largest serving bucket "
            f"(graph_nodes={self.buckets[-1].graph_nodes})")

    # -- scoring ------------------------------------------------------------

    def _padded_batch(self, graphs, bucket: ServeBucket, feat_only=False):
        batch = batch_np(graphs, bucket.spec.max_graphs,
                         bucket.spec.max_nodes, bucket.spec.max_edges)
        if feat_only:
            # an EMPTY group (a replica slot with no requests this window)
            # batches to no feature columns at all — synthesize all-padding
            # ones so every replica's leaf structure matches for stacking
            zeros = np.zeros(bucket.spec.max_nodes, np.int32)
            batch = batch._replace(node_feats={
                k: batch.node_feats.get(k, zeros) for k in self.feat_keys})
        return batch

    def score(self, graphs, bucket: ServeBucket) -> np.ndarray:
        """Pad ``graphs`` (all pre-routed to ``bucket``) and dispatch one
        compiled call; returns the real graphs' probabilities. In latency
        mode this is submit + blocking read — same semantics, one sync."""
        if self.latency_mode:
            return self.submit(graphs, bucket).result()
        if self._stacked_fn is not None:
            return self.score_groups([graphs], bucket)[0]
        faults.raise_if("serve.engine_raises")
        graphs = list(graphs)
        with self._lock:
            batch = self._padded_batch(graphs, bucket)
            fn = self._bucket_fns.get(bucket, self._score_fn)
            probs = np.asarray(fn(batch), np.float32)
            self.n_dispatches += 1
        self._record_dispatch("engine.dispatch", bucket, len(graphs))
        return probs[: len(graphs)]

    def score_groups(self, groups, bucket: ServeBucket) -> list[np.ndarray]:
        """Score up to ``n_replicas`` request groups in ONE dispatch.

        Mesh-replicated engines stack one padded batch per replica on a
        leading device axis (missing replica slots get an all-padding
        batch) and shard-map the stack across the mesh; single-replica
        engines fall back to one :meth:`score` per group. Returns one
        probability array per input group, in order."""
        groups = [list(g) for g in groups]
        if self._stacked_fn is None:
            return [self.score(g, bucket) for g in groups]
        if len(groups) > self.n_replicas:
            raise ValueError(
                f"{len(groups)} groups > {self.n_replicas} replicas — the "
                "batcher must chunk windows to the replica count")
        faults.raise_if("serve.engine_raises")
        with self._lock:
            padded = groups + [[] for _ in range(self.n_replicas - len(groups))]
            batches = [self._padded_batch(g, bucket, feat_only=True)
                       for g in padded]
            import jax

            stacked = jax.tree.map(lambda *xs: np.stack(xs, axis=0), *batches)
            probs = np.asarray(self._stacked_fn(stacked), np.float32)
            self.n_dispatches += 1
        self._record_dispatch("engine.dispatch_stacked", bucket,
                              sum(len(g) for g in groups))
        return [probs[i, : len(g)] for i, g in enumerate(groups)]

    def score_packed(self, graphs) -> np.ndarray:
        """Score a mixed-size request set through the megabatch bucket:
        first-fit-decreasing pack the whole set into as few mega-shaped
        batches as the node/edge/graph budgets allow and dispatch each —
        one dispatch where the per-size-class ladder would walk several.
        Graphs over the mega budget route through the ladder per graph
        (:meth:`assign_bucket` semantics, including
        :class:`OversizeGraphError`). Returns probabilities in input
        order; records the packed batches' padding efficiency in
        ``last_padding_efficiency``."""
        if self.mega_bucket is None:
            raise RuntimeError(
                "score_packed needs a megabatch engine — construct with "
                "from_model(..., megabatch=True) or pass mega=")
        graphs = list(graphs)
        if not graphs:
            return np.zeros(0, np.float32)
        spec = self.mega_bucket.spec
        cap = self.mega_bucket.capacity
        order = sorted(range(len(graphs)),
                       key=lambda i: (-graphs[i].n_nodes,
                                      -graphs[i].n_edges, i))
        bins: list[list[int]] = []
        loads: list[list[int]] = []  # [node-sum, edge-sum] per bin
        overflow: list[int] = []
        for i in order:
            g = graphs[i]
            if g.n_nodes > spec.max_nodes - 1 or g.n_edges > spec.max_edges:
                overflow.append(i)
                continue
            for b, load in zip(bins, loads):
                if (len(b) < cap
                        and load[0] + g.n_nodes <= spec.max_nodes - 1
                        and load[1] + g.n_edges <= spec.max_edges):
                    b.append(i)
                    load[0] += g.n_nodes
                    load[1] += g.n_edges
                    break
            else:
                bins.append([i])
                loads.append([g.n_nodes, g.n_edges])
        out = np.zeros(len(graphs), np.float32)
        for b in bins:
            out[np.asarray(b)] = self.score([graphs[i] for i in b],
                                            self.mega_bucket)
        for i in overflow:
            out[i] = self.score([graphs[i]], self.assign_bucket(graphs[i]))[0]
        if bins:
            real_n = sum(load[0] for load in loads)
            real_e = sum(load[1] for load in loads)
            self.last_padding_efficiency = {
                "nodes": real_n / (len(bins) * spec.max_nodes),
                "edges": real_e / (len(bins) * spec.max_edges),
                "graphs": sum(len(b) for b in bins)
                / (len(bins) * spec.max_graphs),
            }
        return out

    @property
    def hier(self):
        """The lazy :class:`~deepdfa_tpu.models.ggnn_hier.HierScorer` —
        live megabatch-compatible engines only. Attach an embedding cache
        via ``engine.hier.cache = FunctionEmbeddingCache(...)``."""
        with self._lock:
            if self._hier is None:
                if self._hier_factory is None:
                    raise RuntimeError(
                        "score_unit needs a live megabatch-compatible "
                        "engine (graph labels, concat-subkey embeddings) — "
                        "artifact engines and excluded model variants have "
                        "no hierarchical path")
                self._hier = self._hier_factory()
            return self._hier

    def score_unit(self, functions, supergraph) -> dict:
        """Score a merged multi-function unit as ONE request through the
        hierarchical two-level path: per-function level-1 embeddings off
        the fused megabatch kernels (cache-fronted), composed over the
        call graph into a unit score + per-function attribution. Never
        touches the bucket ladder — a unit whose merged CPG would raise
        :class:`OversizeGraphError` scores here per function."""
        faults.raise_if("serve.engine_raises")
        hier = self.hier
        with self._lock:
            before = hier.n_level1_dispatches + hier.n_fallback_dispatches
            out = hier.score_unit(functions, supergraph)
            self.n_dispatches += (hier.n_level1_dispatches
                                  + hier.n_fallback_dispatches - before)
        return out

    def submit(self, graphs, bucket: ServeBucket) -> PendingScore:
        """Latency-mode dispatch: pad, upload, launch — NO host sync. The
        device batch is donated to the warm compiled callable, so the
        launch consumes its input buffers and back-to-back submits pipeline
        on-device instead of round-tripping through the host per request.

        Thread-safe: the pad→upload→launch sequence runs under the engine
        lock, so concurrent callers cannot interleave donated buffers —
        each caller's :class:`PendingScore` owns exactly the device values
        its own dispatch produced."""
        if self._device_fn is None:
            raise RuntimeError(
                "submit() needs a live-model engine (device_fn) — artifact "
                "engines reduce host-side and only support score()")
        faults.raise_if("serve.engine_raises")
        import jax
        import jax.numpy as jnp

        graphs = list(graphs)
        with self._lock:
            batch = self._padded_batch(graphs, bucket, feat_only=True)
            dev = self._device_fn(jax.tree.map(jnp.asarray, batch))
            self.n_dispatches += 1
        self._record_dispatch("engine.submit", bucket, len(graphs))
        return PendingScore(dev, len(graphs))

    # -- warmup + warm store ------------------------------------------------

    def bucket_key(self, bucket: ServeBucket) -> str:
        """Warm-store content address of one bucket's compiled program."""
        from .warmstore import bucket_artifact_key

        return bucket_artifact_key(
            self.vocab_hash, self.model_rev, self.precision,
            self.label_style, self.feat_keys, bucket.spec.max_graphs,
            bucket.spec.max_nodes, bucket.spec.max_edges)

    def _dummy_graph(self) -> Graph:
        n = 2
        feats = {k: np.zeros(n, np.int32) for k in self.feat_keys}
        return Graph(senders=np.arange(n - 1, dtype=np.int32),
                     receivers=np.arange(1, n, dtype=np.int32),
                     node_feats=feats).with_self_loops()

    def _warm_cold(self, bucket: ServeBucket, g: Graph) -> None:
        """Compile the bucket's callable(s) the pre-store way. Calls the
        underlying fns directly, NOT :meth:`score`: the
        ``serve.engine_raises`` fault point poisons a *request's* batch —
        an armed ``@1`` spec must hit the first client, not kill the
        server during startup warmup."""
        if self._stacked_fn is not None:
            batches = [self._padded_batch([g] if i == 0 else [], bucket,
                                          feat_only=True)
                       for i in range(self.n_replicas)]
            import jax

            stacked = jax.tree.map(lambda *xs: np.stack(xs, axis=0), *batches)
            np.asarray(self._stacked_fn(stacked), np.float32)
            return
        batch = self._padded_batch([g], bucket)
        np.asarray(self._score_fn(batch), np.float32)
        if self._device_fn is not None:
            import jax
            import jax.numpy as jnp

            fbatch = batch._replace(node_feats={
                k: batch.node_feats[k] for k in self.feat_keys})
            with warnings.catch_warnings():
                # probs don't alias any int32 input leaf, so XLA reports
                # the donation as unusable at compile — expected here
                warnings.filterwarnings(
                    "ignore", message=".*donated.*", category=UserWarning)
                np.asarray(
                    self._device_fn(jax.tree.map(jnp.asarray, fbatch)))

    def _load_bucket_fn(self, payload: bytes):
        """Deserialize a warm-store payload into this bucket's score_fn
        (same feat-key conformance contract as the live path)."""
        import jax
        import jax.numpy as jnp

        from jax import export as jexport

        from deepdfa_tpu.serving import _register_pytrees

        _register_pytrees()
        exported = jexport.deserialize(payload)

        def fn(batch):
            batch = batch._replace(
                node_feats={k: batch.node_feats[k] for k in self.feat_keys})
            return np.asarray(exported.call(jax.tree.map(jnp.asarray, batch)),
                              np.float32)

        return fn

    def warmup(self, warm_store=None, journal=None) -> dict:
        """Warm every bucket's callable so the first real request never
        pays XLA compilation; returns a report dict (``buckets``, ``hits``,
        ``misses``, ``compile_seconds_saved``, ``per_bucket``).

        With a ``warm_store``, each bucket first tries the store: a HIT
        deserializes the content-addressed exported program (no trace, no
        lowering) and records ``compile_seconds_saved`` = the populating
        replica's recorded compile time minus this load's wall time; a
        MISS compiles cold and, when the engine can export (live
        single-replica, synchronous mode), commits the program for the
        next joiner. Journaled (``event="warmup"``) alongside the
        ``int8_gate_refused`` entries when ``journal`` is given."""
        use_store = (warm_store is not None and self._export_fn is not None
                     and not self.latency_mode)
        g = self._dummy_graph()
        report = {"buckets": len(self.buckets), "hits": 0, "misses": 0,
                  "compile_seconds_saved": 0.0, "per_bucket": {}}
        for b in self.buckets:
            key = self.bucket_key(b) if use_store else None
            entry = warm_store.get(key) if use_store else None
            row: dict = {"key": key}
            if entry is not None:
                t0 = time.perf_counter()
                fn = self._load_bucket_fn(entry.payload)
                fn(self._padded_batch([g], b))  # compiles the StableHLO once
                warm_s = time.perf_counter() - t0
                self._bucket_fns[b] = fn
                recorded = float(entry.meta.get("compile_seconds", 0.0))
                saved = max(0.0, recorded - warm_s)
                report["hits"] += 1
                report["compile_seconds_saved"] += saved
                row.update(source="store", warm_seconds=round(warm_s, 3),
                           compile_seconds=round(recorded, 3),
                           compile_seconds_saved=round(saved, 3))
            else:
                t0 = time.perf_counter()
                self._warm_cold(b, g)
                compile_s = time.perf_counter() - t0
                report["misses"] += 1
                row.update(source="compile",
                           compile_seconds=round(compile_s, 3))
                if use_store:
                    try:
                        payload, export_s = self._export_fn(b)
                        warm_store.put(key, payload, {
                            "compile_seconds": compile_s,
                            "vocab_hash": self.vocab_hash,
                            "model_rev": self.model_rev,
                            "precision": self.precision,
                            "label_style": self.label_style,
                            "graph_nodes": b.graph_nodes,
                            "spec": [b.spec.max_graphs, b.spec.max_nodes,
                                     b.spec.max_edges],
                        })
                        row["export_seconds"] = round(export_s, 3)
                    except Exception as exc:  # noqa: BLE001 — store is an
                        # optimization: a failed export must not take down
                        # warmup (the bucket is already compiled and warm)
                        warnings.warn(
                            f"warm-store export failed for bucket "
                            f"{b.graph_nodes}: {type(exc).__name__}: {exc}",
                            stacklevel=2)
                        row["export_error"] = f"{type(exc).__name__}: {exc}"
            report["per_bucket"][str(b.graph_nodes)] = row
        if self.mega_bucket is not None:
            # the packed-dispatch shape compiles like any ladder bucket;
            # it never exports (warm-store keys are ladder shapes) and is
            # reported under "mega" so ladder rows keep their node keys
            t0 = time.perf_counter()
            self._warm_cold(self.mega_bucket, g)
            report["per_bucket"]["mega"] = {
                "key": None, "source": "compile",
                "compile_seconds": round(time.perf_counter() - t0, 3)}
        report["compile_seconds_saved"] = round(
            report["compile_seconds_saved"], 3)
        self.warm_buckets = [b.graph_nodes for b in self.buckets]
        self.last_warmup_report = report
        if journal is not None:
            journal.write(event="warmup", vocab_hash=self.vocab_hash,
                          model_rev=self.model_rev, precision=self.precision,
                          **report)
        return report

    # -- constructors -------------------------------------------------------

    @classmethod
    def from_model(cls, model, params, label_style: str, feat_keys,
                   max_batch: int = 16, buckets=None,
                   vocab_hash: str | None = None, precision: str = "f32",
                   int8_max_score_delta: float = 0.01,
                   latency_mode: bool = False, calibration_graphs=None,
                   journal=None, mesh=None,
                   megabatch: bool = False) -> "ScoringEngine":
        """Live-model engine (the checkpoint path's core, split out so
        tests can inject fresh params without checkpoint machinery).

        ``precision="int8"`` quantizes the conv matmuls
        (:func:`~deepdfa_tpu.models.ggnn_int8.quantize_conv_params`) and
        GATES the result: f32 and int8 scores are compared on a
        calibration batch per bucket (``calibration_graphs`` or a
        synthesized set) and int8 is REFUSED — engine falls back to f32
        with a warning, journaled when ``journal`` (a ``RunJournal``) is
        given — if the max probability delta exceeds
        ``int8_max_score_delta``. ``latency_mode`` arms :meth:`submit`'s
        warm donated-buffer dispatch path.

        ``mesh`` (a ``jax.sharding.Mesh`` with a ``dp`` axis, e.g.
        :func:`deepdfa_tpu.parallel.mesh.local_mesh`) replicates the
        chosen scorer across every ``dp`` device: the engine scores
        ``dp``-stacked batches device-parallel via :meth:`score_groups`
        and the batcher packs across replicas. Mesh engines dispatch
        synchronously (no donated-buffer submit loop) and keep their
        compiled stack in-process (the warm store serves the
        single-replica router-fleet topology).

        ``megabatch=True`` additionally provisions the :func:`mega_bucket`
        cross-bucket packed-dispatch shape (warmed alongside the ladder)
        so :meth:`score_packed` can score a whole mixed-size request
        window in one dispatch instead of one per size class."""
        import functools

        import jax
        import jax.numpy as jnp

        from deepdfa_tpu.predict import make_scorer

        keys = tuple(feat_keys)
        buckets = tuple(buckets or serve_buckets(max_batch))
        mega = mega_bucket(max_batch) if megabatch else None
        model_rev = _params_content_hash(params)

        def _fns(scorer, ps):
            def score_fn(batch):
                # conform to the warmed pytree structure: request graphs
                # carry extra columns the model never reads (``_VULN``
                # labels) — keep exactly ``feat_keys`` so every batch hits
                # ONE jit cache entry (same policy as serving._Servable)
                batch = batch._replace(
                    node_feats={k: batch.node_feats[k] for k in keys})
                fn_p, _ = scorer(ps, jax.tree.map(jnp.asarray, batch))
                return fn_p

            # the latency-mode entry: batch leaves are donated — the launch
            # consumes them, so a submitted buffer is dead to the host
            @functools.partial(jax.jit, donate_argnums=(0,))
            def device_fn(batch):
                fn_p, _ = scorer(ps, batch)
                return fn_p

            return score_fn, device_fn

        scorer_f32 = make_scorer(model, label_style)
        score_fn, device_fn = _fns(scorer_f32, params)
        chosen_model, chosen_params = model, params
        chosen_scorer = scorer_f32
        int8_delta = None
        if precision == "int8":
            accepted, int8_delta, reason = False, None, None
            try:
                from deepdfa_tpu.models.ggnn_int8 import (
                    GGNNInt8, quantize_conv_params)

                qparams = quantize_conv_params({"params": params})["params"]
                model8 = GGNNInt8(cfg=model.cfg, input_dim=model.input_dim)
                scorer8 = make_scorer(model8, label_style)
                score8, device8 = _fns(scorer8, qparams)
                cal = list(calibration_graphs or
                           _calibration_graphs(keys, buckets))
                int8_delta = 0.0
                for b in buckets:
                    gs = [g for g in cal if b.admits(g)][: b.capacity]
                    if not gs:
                        continue
                    batch = batch_np(gs, b.spec.max_graphs, b.spec.max_nodes,
                                     b.spec.max_edges)
                    p32 = np.asarray(score_fn(batch), np.float32)[: len(gs)]
                    p8 = np.asarray(score8(batch), np.float32)[: len(gs)]
                    int8_delta = max(int8_delta,
                                     float(np.max(np.abs(p32 - p8))))
                accepted = int8_delta <= int8_max_score_delta
                if not accepted:
                    reason = (f"max score delta {int8_delta:.2e} exceeds "
                              f"serve.int8_max_score_delta "
                              f"{int8_max_score_delta:.2e}")
            except ValueError as exc:  # e.g. NaN-poisoned checkpoint kernels
                reason = f"calibration refused: {exc}"
            if accepted:
                score_fn, device_fn = score8, device8
                chosen_model, chosen_params = model8, qparams
                chosen_scorer = scorer8
            else:
                warnings.warn(
                    f"int8 serving path refused — {reason}; serving f32",
                    stacklevel=2)
                if journal is not None:
                    journal.write(event="int8_gate_refused", reason=reason,
                                  int8_max_score_delta=int8_max_score_delta,
                                  int8_score_delta=int8_delta)
                precision = "f32"
        elif precision != "f32":
            raise ValueError(f"precision must be 'f32' or 'int8', got {precision!r}")

        # hierarchical whole-unit path: always the ORIGINAL f32 params —
        # the level-1 bit-identity invariant is pinned against the fused
        # f32 kernels, and the embedding cache keys on their model_rev
        hier_factory = None
        if getattr(model, "cfg", None) is not None:
            from deepdfa_tpu.models.ggnn_hier import (
                HierScorer, megabatch_compatible)

            if megabatch_compatible(model.cfg):
                hier_factory = (lambda m=model, p=params, rev=model_rev:
                                HierScorer(m.cfg, m.input_dim, p,
                                           model_rev=rev))

        if mesh is not None:
            stacked_fn = _make_replicated_fn(chosen_scorer, chosen_params,
                                             mesh)
            return cls(None, buckets, label_style=label_style,
                       feat_keys=keys, vocab_hash=vocab_hash,
                       latency_mode=latency_mode, precision=precision,
                       int8_score_delta=int8_delta, stacked_fn=stacked_fn,
                       n_replicas=int(mesh.shape["dp"]), model_rev=model_rev,
                       mega=mega, hier_factory=hier_factory)

        export_fn = _make_export_fn(chosen_model, chosen_params, label_style,
                                    keys)
        return cls(score_fn, buckets, label_style=label_style,
                   feat_keys=keys, vocab_hash=vocab_hash,
                   device_fn=device_fn, latency_mode=latency_mode,
                   precision=precision, int8_score_delta=int8_delta,
                   model_rev=model_rev, export_fn=export_fn, mega=mega,
                   hier_factory=hier_factory)

    @classmethod
    def from_checkpoint(cls, cfg, ckpt_dir: Path | str, vocabs,
                        max_batch: int | None = None,
                        journal=None) -> "ScoringEngine":
        """Restore best-else-latest params (same policy as predict/test)
        and serve through the layout-portable segment forward. With
        ``cfg.serve.mesh_replicas > 1`` the engine replicates across that
        many local devices (one replica per device)."""
        import jax
        import jax.numpy as jnp

        from deepdfa_tpu.models import make_model
        from deepdfa_tpu.pipeline import vocab_content_hash
        from deepdfa_tpu.train.checkpoint import CheckpointManager

        if cfg.model.layout != "segment":
            cfg = dataclasses.replace(
                cfg, model=dataclasses.replace(cfg.model, layout="segment"))
        model = make_model(cfg.model, cfg.input_dim)
        n = 4
        feats = {k: np.zeros(n, np.int32) for k in vocabs}
        feats["_VULN"] = np.zeros(n, np.int32)
        dummy = Graph(senders=np.arange(n - 1, dtype=np.int32),
                      receivers=np.arange(1, n, dtype=np.int32),
                      node_feats=feats).with_self_loops()
        example = jax.tree.map(jnp.asarray, batch_np([dummy], 2, 8, 128))
        params = model.init(jax.random.key(0), example)["params"]
        ckpts = CheckpointManager(Path(ckpt_dir), cfg.checkpoint)
        if ckpts.latest_step() is None:
            raise FileNotFoundError(
                f"no checkpoint under {ckpt_dir} — the engine serves a "
                "TRAINED model; run fit first (or point at an --artifact)")
        restored = (ckpts.restore_best(template={"params": params})
                    if ckpts.best_step() is not None
                    else ckpts.restore_latest(template={"params": params}))
        mesh = None
        if getattr(cfg.serve, "mesh_replicas", 0) > 1:
            from deepdfa_tpu.parallel.mesh import local_mesh

            mesh = local_mesh(cfg.serve.mesh_replicas)
        return cls.from_model(
            model, restored["params"], cfg.model.label_style,
            feat_keys=tuple(vocabs),
            max_batch=max_batch or cfg.serve.max_batch,
            vocab_hash=vocab_content_hash(vocabs),
            precision=cfg.serve.precision,
            int8_max_score_delta=cfg.serve.int8_max_score_delta,
            latency_mode=cfg.serve.latency_mode, journal=journal, mesh=mesh)

    @classmethod
    def from_artifact(cls, artifact_dir: Path | str,
                      vocabs=None) -> "ScoringEngine":
        """Engine over a pre-exported StableHLO artifact. The artifact is
        compiled for ONE shape, so the ladder collapses to one bucket at
        the manifest's budgets. When ``vocabs`` is given, its content hash
        is checked against the manifest (``load_exported`` warns on
        mismatch — the stale-artifact guard)."""
        from deepdfa_tpu.serving import load_exported

        vocab_hash = None
        if vocabs is not None:
            from deepdfa_tpu.pipeline import vocab_content_hash

            vocab_hash = vocab_content_hash(vocabs)
        servable = load_exported(artifact_dir, expect_vocab_hash=vocab_hash)
        man = servable.manifest
        leaves = man["input_leaves"]
        # flatten order: node_feats (sorted keys), senders, receivers,
        # node_gidx, node_mask, edge_mask, graph_mask
        max_graphs = int(leaves[-1]["shape"][0])
        max_edges = int(leaves[-2]["shape"][0])
        max_nodes = int(leaves[-3]["shape"][0])
        spec = BucketSpec(max_graphs, max_nodes, max_edges)
        bucket = ServeBucket(spec=spec, graph_nodes=max_nodes - 1)
        label_style = man.get("label_style", "graph")

        if label_style == "node":
            def score_fn(batch):
                node_p = np.asarray(servable(batch), np.float32)
                fn = np.zeros(batch.max_graphs, np.float32)
                mask = np.asarray(batch.node_mask)
                np.maximum.at(
                    fn, np.asarray(batch.node_gidx)[mask], node_p[mask])
                return fn
        else:
            score_fn = servable
        return cls(score_fn, (bucket,), label_style=label_style,
                   feat_keys=tuple(man["node_feat_keys"]),
                   vocab_hash=man.get("vocab_hash"))


# ---------------------------------------------------------------------------
# mesh replication + warm-store export helpers (live-model engines)


def _plain_score_callable(model, params, label_style: str):
    """The exportable form of the scorer: plain apply (no mutable
    intermediates — jax.export cannot serialize them), same probabilities
    as :func:`deepdfa_tpu.predict.make_scorer`. Node-style checkpoints
    bake the node→function max reduction into the program."""
    import jax
    import jax.numpy as jnp

    def score(batch):
        if label_style == "node":
            node_p = jax.nn.sigmoid(model.apply({"params": params}, batch))
            masked = jnp.where(batch.node_mask, node_p,
                               jnp.full_like(node_p, -jnp.inf))
            return jax.ops.segment_max(masked, batch.node_gidx,
                                       num_segments=batch.max_graphs)
        return jax.nn.sigmoid(model.apply({"params": params}, batch))

    return score


def _make_export_fn(model, params, label_style: str, feat_keys):
    """``bucket -> (serialized StableHLO, export_seconds)`` for the warm
    store — the same ``jax.export`` path :func:`deepdfa_tpu.serving.
    export_ggnn` uses, specialized to one bucket's padded shape."""

    def export_bucket(bucket: ServeBucket):
        import jax

        from jax import export as jexport

        from deepdfa_tpu.serving import _register_pytrees

        _register_pytrees()
        t0 = time.perf_counter()
        n = 2
        feats = {k: np.zeros(n, np.int32) for k in feat_keys}
        g = Graph(senders=np.arange(n - 1, dtype=np.int32),
                  receivers=np.arange(1, n, dtype=np.int32),
                  node_feats=feats).with_self_loops()
        ex = batch_np([g], bucket.spec.max_graphs, bucket.spec.max_nodes,
                      bucket.spec.max_edges)
        ex = ex._replace(node_feats={k: ex.node_feats[k] for k in feat_keys})
        args_spec = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype),
            ex)
        score = _plain_score_callable(model, params, label_style)
        exported = jexport.export(jax.jit(score),
                                  platforms=["cpu", "tpu"])(args_spec)
        return exported.serialize(), time.perf_counter() - t0

    return export_bucket


def _make_replicated_fn(scorer, params, mesh):
    """One-dispatch device-parallel scoring over a ``dp`` mesh: the
    stacked ``[dp, ...]`` batch splits one padded batch per device
    (shard_map), each replica runs the scorer locally, and the probs come
    back stacked ``[dp, max_graphs]``. Params are replicated — no
    collectives exist in this program at all; it is pure replication."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from deepdfa_tpu.parallel.dp import _shard_map

    def one(ps, stacked):
        batch = jax.tree.map(lambda x: x[0], stacked)
        fn_p, _ = scorer(ps, batch)
        return fn_p[None]

    replicated = jax.jit(_shard_map(
        one, mesh=mesh, in_specs=(P(), P("dp")), out_specs=P("dp"),
        check_vma=False))

    def stacked_fn(stacked):
        return np.asarray(
            replicated(params, jax.tree.map(jnp.asarray, stacked)),
            np.float32)

    return stacked_fn
