"""ScoringEngine — warm per-bucket compiled scorers for the online path.

The compiled-shape discipline that rules training rules serving too: XLA
programs are specialized to static shapes, so the engine owns a small
ladder of :class:`~deepdfa_tpu.data.graphs.BucketSpec` budgets (size
classes per *graph*, batch budgets per *bucket*) and keeps one compiled
callable warm per bucket. Requests are routed to the smallest size class
that fits their graph (`assign_bucket`), the batcher packs per class, and
`score` pads + dispatches — after the first `warmup()` no request ever
pays a compile.

Two constructors, one contract:

- :meth:`from_checkpoint` — live model + restored params through
  :func:`deepdfa_tpu.predict.make_scorer` (jit; any bucket ladder);
- :meth:`from_artifact` — a pre-exported StableHLO artifact
  (:mod:`deepdfa_tpu.serving`), whose ONE baked shape becomes the only
  bucket; node-label artifacts are reduced to function scores host-side.

`score` is where the ``serve.engine_raises`` fault point lives: an
injected (or real) engine failure must surface as a per-request error in
the batcher, never as a dead server.
"""

from __future__ import annotations

import dataclasses
import warnings
from pathlib import Path

import numpy as np

from deepdfa_tpu.data.graphs import BucketSpec, Graph, _round_up, batch_np
from deepdfa_tpu.resilience import faults

__all__ = ["OversizeGraphError", "ServeBucket", "serve_buckets",
           "ScoringEngine", "PendingScore"]


class OversizeGraphError(ValueError):
    """The function's graph exceeds every serving bucket — a per-request
    413, not a reason to grow the compiled-shape ladder at runtime."""


@dataclasses.dataclass(frozen=True)
class ServeBucket:
    """A size class: graphs with ``n_nodes <= graph_nodes`` (and edges
    within the per-graph share) route here; ``spec`` is the padded batch
    budget the bucket's compiled callable is specialized to."""

    spec: BucketSpec
    graph_nodes: int

    @property
    def capacity(self) -> int:
        """Real-graph slots (one BucketSpec slot is the padding sink)."""
        return self.spec.max_graphs - 1

    def admits(self, g: Graph) -> bool:
        return (g.n_nodes <= self.graph_nodes
                and g.n_edges <= 4 * self.graph_nodes
                and self.spec.fits(1, g.n_nodes, g.n_edges))


def serve_buckets(max_batch: int) -> tuple[ServeBucket, ...]:
    """The default ladder: small CFGs (DeepDFA's regime, ~50 nodes) batch
    ``max_batch``-wide; mid-size functions batch narrower; huge ones go
    one-per-batch. Three compiled shapes total — bounded compile cost,
    bounded padding waste."""
    ladder = ((126, max_batch), (1022, max(1, max_batch // 4)), (4094, 1))
    out = []
    for per_graph, gcap in ladder:
        nn = _round_up(gcap * per_graph + 2)
        out.append(ServeBucket(
            spec=BucketSpec(gcap + 1, nn, 4 * nn), graph_nodes=per_graph))
    return tuple(out)


def _calibration_graphs(feat_keys, buckets, n_per_bucket: int = 4,
                        seed: int = 0):
    """Synthesized int8-gate inputs when the caller has no realworld
    fixtures handy: a few random graphs per bucket size class (feature ids
    in {0, 1} — valid rows in every embedding table). Deterministic
    (seeded) so the gate verdict is reproducible across engine builds."""
    rng = np.random.default_rng(seed)
    out = []
    for b in buckets:
        cap = min(b.graph_nodes, 48)
        for _ in range(n_per_bucket):
            n = int(rng.integers(max(2, cap // 2), cap + 1))
            feats = {k: rng.integers(0, 2, size=n).astype(np.int32)
                     for k in feat_keys}
            out.append(Graph(
                senders=rng.integers(0, n, size=2 * n).astype(np.int32),
                receivers=rng.integers(0, n, size=2 * n).astype(np.int32),
                node_feats=feats).with_self_loops())
    return out


class PendingScore:
    """Handle returned by :meth:`ScoringEngine.submit` — the scores stay
    device-resident (no host sync at dispatch); :meth:`result` is the one
    blocking read."""

    __slots__ = ("_dev", "_n")

    def __init__(self, dev, n: int):
        self._dev = dev
        self._n = n

    def result(self) -> np.ndarray:
        return np.asarray(self._dev, np.float32)[: self._n]


class ScoringEngine:
    """``score(graphs, bucket) -> fn_prob[len(graphs)]`` over a fixed
    bucket ladder. ``score_fn`` maps a padded ``BatchedGraphs`` to
    per-graph probabilities ``[max_graphs]`` (already sigmoid'd).

    ``device_fn`` (optional — the live-model constructors set it): a jitted
    ``device batch -> device probs`` callable whose batch argument is
    DONATED, enabling ``latency_mode`` — :meth:`submit` dispatches without
    any host sync and hands back a :class:`PendingScore`; the input buffers
    are consumed by the dispatch (donation) so a submitted batch is never
    reused host-side. ``precision`` records which weight path the engine
    serves (``f32`` or ``int8``); ``int8_score_delta`` the measured
    calibration-batch gate value when int8 was requested."""

    def __init__(self, score_fn, buckets, label_style: str = "graph",
                 feat_keys=(), vocab_hash: str | None = None,
                 device_fn=None, latency_mode: bool = False,
                 precision: str = "f32",
                 int8_score_delta: float | None = None):
        if not buckets:
            raise ValueError("need at least one serving bucket")
        self._score_fn = score_fn
        self._device_fn = device_fn
        if latency_mode and device_fn is None:
            warnings.warn(
                "latency_mode requires a jit-safe device_fn (live-model "
                "engines only — StableHLO artifact reductions run host-side); "
                "serving in synchronous mode", stacklevel=2)
            latency_mode = False
        self.latency_mode = latency_mode
        self.precision = precision
        self.int8_score_delta = int8_score_delta
        self.buckets = tuple(sorted(
            buckets, key=lambda b: (b.graph_nodes, b.spec.max_graphs)))
        self.label_style = label_style
        self.feat_keys = tuple(feat_keys)
        self.vocab_hash = vocab_hash
        self.n_dispatches = 0

    # -- routing ------------------------------------------------------------

    def assign_bucket(self, g: Graph) -> ServeBucket:
        for b in self.buckets:
            if b.admits(g):
                return b
        raise OversizeGraphError(
            f"graph with {g.n_nodes} nodes / {g.n_edges} edges exceeds the "
            f"largest serving bucket "
            f"(graph_nodes={self.buckets[-1].graph_nodes})")

    # -- scoring ------------------------------------------------------------

    def score(self, graphs, bucket: ServeBucket) -> np.ndarray:
        """Pad ``graphs`` (all pre-routed to ``bucket``) and dispatch one
        compiled call; returns the real graphs' probabilities. In latency
        mode this is submit + blocking read — same semantics, one sync."""
        if self.latency_mode:
            return self.submit(graphs, bucket).result()
        faults.raise_if("serve.engine_raises")
        graphs = list(graphs)
        batch = batch_np(graphs, bucket.spec.max_graphs,
                         bucket.spec.max_nodes, bucket.spec.max_edges)
        probs = np.asarray(self._score_fn(batch), np.float32)
        self.n_dispatches += 1
        return probs[: len(graphs)]

    def submit(self, graphs, bucket: ServeBucket) -> PendingScore:
        """Latency-mode dispatch: pad, upload, launch — NO host sync. The
        device batch is donated to the warm compiled callable, so the
        launch consumes its input buffers and back-to-back submits pipeline
        on-device instead of round-tripping through the host per request."""
        if self._device_fn is None:
            raise RuntimeError(
                "submit() needs a live-model engine (device_fn) — artifact "
                "engines reduce host-side and only support score()")
        faults.raise_if("serve.engine_raises")
        import jax
        import jax.numpy as jnp

        graphs = list(graphs)
        batch = batch_np(graphs, bucket.spec.max_graphs,
                         bucket.spec.max_nodes, bucket.spec.max_edges)
        batch = batch._replace(
            node_feats={k: batch.node_feats[k] for k in self.feat_keys})
        dev = self._device_fn(jax.tree.map(jnp.asarray, batch))
        self.n_dispatches += 1
        return PendingScore(dev, len(graphs))

    def warmup(self) -> int:
        """Compile every bucket's callable on a dummy graph so the first
        real request never pays XLA compilation; returns buckets warmed.
        Calls ``score_fn`` directly, NOT :meth:`score`: the
        ``serve.engine_raises`` fault point poisons a *request's* batch —
        an armed ``@1`` spec must hit the first client, not kill the
        server during startup warmup."""
        n = 2
        feats = {k: np.zeros(n, np.int32) for k in self.feat_keys}
        g = Graph(senders=np.arange(n - 1, dtype=np.int32),
                  receivers=np.arange(1, n, dtype=np.int32),
                  node_feats=feats).with_self_loops()
        for b in self.buckets:
            batch = batch_np([g], b.spec.max_graphs, b.spec.max_nodes,
                             b.spec.max_edges)
            np.asarray(self._score_fn(batch), np.float32)
            if self._device_fn is not None:
                import jax
                import jax.numpy as jnp

                fbatch = batch._replace(node_feats={
                    k: batch.node_feats[k] for k in self.feat_keys})
                with warnings.catch_warnings():
                    # probs don't alias any int32 input leaf, so XLA reports
                    # the donation as unusable at compile — expected here
                    warnings.filterwarnings(
                        "ignore", message=".*donated.*", category=UserWarning)
                    np.asarray(
                        self._device_fn(jax.tree.map(jnp.asarray, fbatch)))
        return len(self.buckets)

    # -- constructors -------------------------------------------------------

    @classmethod
    def from_model(cls, model, params, label_style: str, feat_keys,
                   max_batch: int = 16, buckets=None,
                   vocab_hash: str | None = None, precision: str = "f32",
                   int8_max_score_delta: float = 0.01,
                   latency_mode: bool = False, calibration_graphs=None,
                   journal=None) -> "ScoringEngine":
        """Live-model engine (the checkpoint path's core, split out so
        tests can inject fresh params without checkpoint machinery).

        ``precision="int8"`` quantizes the conv matmuls
        (:func:`~deepdfa_tpu.models.ggnn_int8.quantize_conv_params`) and
        GATES the result: f32 and int8 scores are compared on a
        calibration batch per bucket (``calibration_graphs`` or a
        synthesized set) and int8 is REFUSED — engine falls back to f32
        with a warning, journaled when ``journal`` (a ``RunJournal``) is
        given — if the max probability delta exceeds
        ``int8_max_score_delta``. ``latency_mode`` arms :meth:`submit`'s
        warm donated-buffer dispatch path."""
        import functools

        import jax
        import jax.numpy as jnp

        from deepdfa_tpu.predict import make_scorer

        keys = tuple(feat_keys)
        buckets = tuple(buckets or serve_buckets(max_batch))

        def _fns(scorer, ps):
            def score_fn(batch):
                # conform to the warmed pytree structure: request graphs
                # carry extra columns the model never reads (``_VULN``
                # labels) — keep exactly ``feat_keys`` so every batch hits
                # ONE jit cache entry (same policy as serving._Servable)
                batch = batch._replace(
                    node_feats={k: batch.node_feats[k] for k in keys})
                fn_p, _ = scorer(ps, jax.tree.map(jnp.asarray, batch))
                return fn_p

            # the latency-mode entry: batch leaves are donated — the launch
            # consumes them, so a submitted buffer is dead to the host
            @functools.partial(jax.jit, donate_argnums=(0,))
            def device_fn(batch):
                fn_p, _ = scorer(ps, batch)
                return fn_p

            return score_fn, device_fn

        scorer_f32 = make_scorer(model, label_style)
        score_fn, device_fn = _fns(scorer_f32, params)
        int8_delta = None
        if precision == "int8":
            accepted, int8_delta, reason = False, None, None
            try:
                from deepdfa_tpu.models.ggnn_int8 import (
                    GGNNInt8, quantize_conv_params)

                qparams = quantize_conv_params({"params": params})["params"]
                model8 = GGNNInt8(cfg=model.cfg, input_dim=model.input_dim)
                score8, device8 = _fns(make_scorer(model8, label_style), qparams)
                cal = list(calibration_graphs or
                           _calibration_graphs(keys, buckets))
                int8_delta = 0.0
                for b in buckets:
                    gs = [g for g in cal if b.admits(g)][: b.capacity]
                    if not gs:
                        continue
                    batch = batch_np(gs, b.spec.max_graphs, b.spec.max_nodes,
                                     b.spec.max_edges)
                    p32 = np.asarray(score_fn(batch), np.float32)[: len(gs)]
                    p8 = np.asarray(score8(batch), np.float32)[: len(gs)]
                    int8_delta = max(int8_delta,
                                     float(np.max(np.abs(p32 - p8))))
                accepted = int8_delta <= int8_max_score_delta
                if not accepted:
                    reason = (f"max score delta {int8_delta:.2e} exceeds "
                              f"serve.int8_max_score_delta "
                              f"{int8_max_score_delta:.2e}")
            except ValueError as exc:  # e.g. NaN-poisoned checkpoint kernels
                reason = f"calibration refused: {exc}"
            if accepted:
                score_fn, device_fn = score8, device8
            else:
                warnings.warn(
                    f"int8 serving path refused — {reason}; serving f32",
                    stacklevel=2)
                if journal is not None:
                    journal.write(event="int8_gate_refused", reason=reason,
                                  int8_max_score_delta=int8_max_score_delta,
                                  int8_score_delta=int8_delta)
                precision = "f32"
        elif precision != "f32":
            raise ValueError(f"precision must be 'f32' or 'int8', got {precision!r}")

        return cls(score_fn, buckets, label_style=label_style,
                   feat_keys=feat_keys, vocab_hash=vocab_hash,
                   device_fn=device_fn, latency_mode=latency_mode,
                   precision=precision, int8_score_delta=int8_delta)

    @classmethod
    def from_checkpoint(cls, cfg, ckpt_dir: Path | str, vocabs,
                        max_batch: int | None = None) -> "ScoringEngine":
        """Restore best-else-latest params (same policy as predict/test)
        and serve through the layout-portable segment forward."""
        import jax
        import jax.numpy as jnp

        from deepdfa_tpu.models import make_model
        from deepdfa_tpu.pipeline import vocab_content_hash
        from deepdfa_tpu.train.checkpoint import CheckpointManager

        if cfg.model.layout != "segment":
            cfg = dataclasses.replace(
                cfg, model=dataclasses.replace(cfg.model, layout="segment"))
        model = make_model(cfg.model, cfg.input_dim)
        n = 4
        feats = {k: np.zeros(n, np.int32) for k in vocabs}
        feats["_VULN"] = np.zeros(n, np.int32)
        dummy = Graph(senders=np.arange(n - 1, dtype=np.int32),
                      receivers=np.arange(1, n, dtype=np.int32),
                      node_feats=feats).with_self_loops()
        example = jax.tree.map(jnp.asarray, batch_np([dummy], 2, 8, 128))
        params = model.init(jax.random.key(0), example)["params"]
        ckpts = CheckpointManager(Path(ckpt_dir), cfg.checkpoint)
        if ckpts.latest_step() is None:
            raise FileNotFoundError(
                f"no checkpoint under {ckpt_dir} — the engine serves a "
                "TRAINED model; run fit first (or point at an --artifact)")
        restored = (ckpts.restore_best(template={"params": params})
                    if ckpts.best_step() is not None
                    else ckpts.restore_latest(template={"params": params}))
        return cls.from_model(
            model, restored["params"], cfg.model.label_style,
            feat_keys=tuple(vocabs),
            max_batch=max_batch or cfg.serve.max_batch,
            vocab_hash=vocab_content_hash(vocabs),
            precision=cfg.serve.precision,
            int8_max_score_delta=cfg.serve.int8_max_score_delta,
            latency_mode=cfg.serve.latency_mode)

    @classmethod
    def from_artifact(cls, artifact_dir: Path | str,
                      vocabs=None) -> "ScoringEngine":
        """Engine over a pre-exported StableHLO artifact. The artifact is
        compiled for ONE shape, so the ladder collapses to one bucket at
        the manifest's budgets. When ``vocabs`` is given, its content hash
        is checked against the manifest (``load_exported`` warns on
        mismatch — the stale-artifact guard)."""
        from deepdfa_tpu.serving import load_exported

        vocab_hash = None
        if vocabs is not None:
            from deepdfa_tpu.pipeline import vocab_content_hash

            vocab_hash = vocab_content_hash(vocabs)
        servable = load_exported(artifact_dir, expect_vocab_hash=vocab_hash)
        man = servable.manifest
        leaves = man["input_leaves"]
        # flatten order: node_feats (sorted keys), senders, receivers,
        # node_gidx, node_mask, edge_mask, graph_mask
        max_graphs = int(leaves[-1]["shape"][0])
        max_edges = int(leaves[-2]["shape"][0])
        max_nodes = int(leaves[-3]["shape"][0])
        spec = BucketSpec(max_graphs, max_nodes, max_edges)
        bucket = ServeBucket(spec=spec, graph_nodes=max_nodes - 1)
        label_style = man.get("label_style", "graph")

        if label_style == "node":
            def score_fn(batch):
                node_p = np.asarray(servable(batch), np.float32)
                fn = np.zeros(batch.max_graphs, np.float32)
                mask = np.asarray(batch.node_mask)
                np.maximum.at(
                    fn, np.asarray(batch.node_gidx)[mask], node_p[mask])
                return fn
        else:
            score_fn = servable
        return cls(score_fn, (bucket,), label_style=label_style,
                   feat_keys=tuple(man["node_feat_keys"]),
                   vocab_hash=man.get("vocab_hash"))
