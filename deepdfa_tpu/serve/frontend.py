"""Frontend encode pool: cold-request ``encode_source`` past the GIL.

The serving cold path runs the whole source→CPG→dataflow→feature
pipeline in pure Python; inline on the request-handler thread, N
concurrent cold requests serialize on the GIL while the device idles
between dispatches. :class:`FrontendPool` moves that work onto N encode
workers built from the extraction-pool primitives (PR 13):

- each worker owns its own deque and **steals** from the back of the
  longest other queue when it runs dry (one slow file stalls one worker,
  never the fleet); a shared overflow deque carries crash-requeued
  in-flight items;
- ``mode="process"`` workers are :class:`FrontendProcessSession`\\ s —
  **spawned** children that warm-load the vocabularies once and encode
  until told to stop, so encode runs in true parallel past the GIL and
  overlaps the micro-batcher's device dispatches. The spawn handshake
  carries the child's vocabulary content hash; a mismatch with the
  serving vocabs raises :class:`VocabHashMismatch` and fails the pool
  fast — divergent vocabularies would silently score garbage;
- ``mode="thread"`` keeps the sessions in-process (cheap, deterministic
  under test; still overlaps dispatch at I/O boundaries);
- every worker session sits behind an
  :class:`~deepdfa_tpu.resilience.supervisor.ExtractionSupervisor`
  (spawn retry with backoff, restart-on-failure, quarantine-on-repeat);
- the queue is **bounded** (:class:`~.batcher.QueueFullError` beyond
  ``max_queue`` — the same admission-control contract as the
  micro-batcher), ``stop(drain=True)`` is the flag-only SIGTERM drain
  (invariants 6/12), and the ``frontend.worker_crash`` chaos point
  re-queues the crashed worker's in-flight item onto the overflow deque
  — completed exactly once by a survivor, never lost, never
  double-completed (invariant 23's pool semantics).

Failure classification for the server: :data:`ENCODE_ITEM_ERRORS`
members mean *the item* failed to encode (the request's 422 row); any
other exception means *the pool* failed — the server degrades to inline
encode and never converts pool trouble into a new 5xx (standing
invariant 25).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Callable

from deepdfa_tpu.data.extraction import ExtractionItemError
from deepdfa_tpu.resilience import faults
from deepdfa_tpu.resilience.retry import RetryPolicy
from deepdfa_tpu.resilience.supervisor import (
    ExtractionSupervisor,
    QuarantinedError,
)

from .batcher import QueueFullError

__all__ = [
    "ENCODE_ITEM_ERRORS",
    "FrontendPool",
    "FrontendProcessSession",
    "ThreadEncodeSession",
    "VocabHashMismatch",
    "encode_session_factory",
]

logger = logging.getLogger("deepdfa_tpu")

# the ITEM failed to encode (the caller's 422-row protocol); everything
# else implicates the pool and must degrade to inline encode instead
ENCODE_ITEM_ERRORS: tuple[type[BaseException], ...] = (
    ExtractionItemError, QuarantinedError)


class VocabHashMismatch(ValueError):
    """A frontend worker warm-loaded vocabularies whose content hash
    disagrees with the serving vocabs — encoding with them would score
    garbage, so the spawn fails fast (a ValueError: the supervisor's
    spawn retry must NOT retry a deterministic config error)."""


class _FrontendWorkerCrashed(BaseException):
    """Internal: tears down one worker thread; never crosses submit()."""

    def __init__(self, worker_id: int):
        super().__init__(f"frontend worker {worker_id} crashed")
        self.worker_id = worker_id


# ---------------------------------------------------------------------------
# encode sessions: the same supervision contract as extraction sessions


class ThreadEncodeSession:
    """In-process encode session: one vocab closure. Every encode failure
    is an :class:`ExtractionItemError` — in-process there is no session
    infrastructure to implicate, only the item.

    ``keep_cpg=False`` (the default) returns (name, Graph, node_ids) only —
    small, picklable, exactly what scoring needs. The interproc scan flips
    it on so the supergraph pass reuses the already-parsed per-function
    CPGs instead of parsing every source a second time; in-process there
    is no pickle boundary, so the CPGs ride along for free."""

    def __init__(self, vocabs, *, keep_cpg: bool = False):
        self._vocabs = vocabs
        self._keep_cpg = keep_cpg

    def encode(self, code: str):
        from deepdfa_tpu.pipeline import encode_source

        try:
            return encode_source(code, self._vocabs, keep_cpg=self._keep_cpg)
        except Exception as exc:  # noqa: BLE001 — item error by definition
            raise ExtractionItemError(f"{type(exc).__name__}: {exc}") from exc

    def close(self) -> None:
        pass


def _frontend_child_main(conn, vocab_blob) -> None:
    """Child loop: warm-load the vocabs ONCE, report their content hash
    in the ready handshake, then encode sources until EOF. Item failures
    are replied (not raised) — only a genuinely dead child implicates
    the session."""
    try:
        from deepdfa_tpu.pipeline import (
            encode_source,
            load_vocabs,
            vocab_content_hash,
        )

        vocabs = (load_vocabs(vocab_blob) if isinstance(vocab_blob, str)
                  else vocab_blob)
        vhash = vocab_content_hash(vocabs)
    except Exception as exc:  # noqa: BLE001 — reported to the parent
        try:
            conn.send(("spawn_error", f"{type(exc).__name__}: {exc}"))
        finally:
            conn.close()
        return
    conn.send(("ready", vhash))
    while True:
        try:
            kind, payload = conn.recv()
        except (EOFError, OSError):
            return
        if kind == "stop":
            conn.close()
            return
        try:
            conn.send(("ok", encode_source(payload, vocabs, keep_cpg=False)))
        except Exception as exc:  # noqa: BLE001 — item error, session lives
            conn.send(("err", f"{type(exc).__name__}: {exc}"))


class FrontendProcessSession:
    """An encode session in a dedicated **spawned** child (spawn-safe;
    fork after jax init can deadlock). ``vocab_blob`` is either a shard
    directory path (the child warm-loads from disk) or the vocab dict
    itself (pickled through the spawn args). The ready handshake carries
    the child's vocab content hash; disagreement with ``expect_hash``
    raises :class:`VocabHashMismatch` immediately. A dead/hung child
    raises ``SESSION_ERRORS`` members so the supervisor respawns it;
    encode-level failures raise :class:`ExtractionItemError` and leave
    the session alive."""

    def __init__(self, vocab_blob, *, expect_hash: str,
                 timeout_s: float = 120.0, spawn_timeout_s: float = 120.0):
        import multiprocessing

        self.timeout_s = timeout_s
        ctx = multiprocessing.get_context("spawn")
        self._conn, child = ctx.Pipe()
        self._proc = ctx.Process(
            target=_frontend_child_main, args=(child, vocab_blob), daemon=True)
        self._proc.start()
        child.close()
        if not self._conn.poll(spawn_timeout_s):
            self.close()
            raise TimeoutError(
                f"frontend session did not report ready in {spawn_timeout_s}s")
        try:
            kind, detail = self._conn.recv()
        except (EOFError, OSError) as exc:
            self.close()
            raise RuntimeError("frontend session died during spawn") from exc
        if kind != "ready":
            self.close()
            raise RuntimeError(f"frontend session failed to spawn: {detail}")
        if detail != expect_hash:
            self.close()
            raise VocabHashMismatch(
                f"frontend worker warm-loaded vocab hash {detail} but the "
                f"server serves {expect_hash} — refusing to encode with "
                "divergent vocabularies")
        self.vocab_hash = detail

    def encode(self, source: str, timeout_s: float | None = None):
        timeout_s = self.timeout_s if timeout_s is None else timeout_s
        try:
            self._conn.send(("item", source))
        except (OSError, ValueError) as exc:
            raise RuntimeError(
                f"frontend session pipe is dead: {exc}") from exc
        if not self._conn.poll(timeout_s):
            raise TimeoutError(
                f"frontend session gave no reply within {timeout_s}s")
        try:
            kind, out = self._conn.recv()
        except (EOFError, OSError) as exc:
            raise RuntimeError("frontend session died mid-item") from exc
        if kind == "ok":
            return out
        raise ExtractionItemError(out)

    def close(self) -> None:
        try:
            self._conn.send(("stop", None))
        except (OSError, ValueError):
            pass
        self._conn.close()
        self._proc.join(timeout=2.0)
        if self._proc.is_alive():
            self._proc.terminate()
            self._proc.join(timeout=2.0)


def encode_session_factory(vocabs, fcfg, *, vocab_source=None,
                           keep_cpg: bool = False) -> Callable:
    """One ``session_factory(worker_id)`` for BOTH frontends: the online
    :class:`FrontendPool` and the offline scan's
    :class:`~deepdfa_tpu.data.extraction.ExtractionPool` build their
    encode sessions here, so mode/handshake/timeout semantics cannot
    drift between the two surfaces. ``vocab_source`` (a shard dir) makes
    process children warm-load from disk instead of pickling the vocabs
    through the spawn args.

    ``keep_cpg`` applies to thread sessions only: process children always
    drop the CPG (it would have to pickle back through the pipe per item
    — the interproc scan's parse-reuse degrades to a re-parse in process
    mode, which the scan reports honestly)."""
    from deepdfa_tpu.pipeline import vocab_content_hash

    expect_hash = vocab_content_hash(vocabs)
    blob = str(vocab_source) if vocab_source is not None else vocabs

    def factory(worker_id: int = 0):
        faults.raise_if("frontend.spawn_fail")
        if fcfg.mode == "process":
            return FrontendProcessSession(
                blob, expect_hash=expect_hash,
                timeout_s=fcfg.encode_timeout_s,
                spawn_timeout_s=fcfg.spawn_timeout_s)
        return ThreadEncodeSession(vocabs, keep_cpg=keep_cpg)

    return factory


# ---------------------------------------------------------------------------
# the pool


class _FrontendTask:
    __slots__ = ("key", "source", "future", "ctx", "submitted_mono",
                 "done")

    def __init__(self, key, source, ctx):
        self.key = key
        self.source = source
        self.future: Future = Future()
        self.ctx = ctx
        self.submitted_mono = time.monotonic()
        self.done = False


class FrontendPool:
    """``submit(source)`` → Future resolving to the encoded functions,
    through N long-lived supervised encode workers. Unlike
    :class:`~deepdfa_tpu.data.extraction.ExtractionPool` (batch
    ``run()``/join), this pool serves an open-ended request stream:
    workers block on a condition, the queue is bounded, and shutdown is
    the flag-only drain the server's SIGTERM handler drives."""

    def __init__(self, vocabs, cfg, *, metrics=None, tracer=None,
                 vocab_source=None, attempts_per_item: int = 2,
                 spawn_policy: RetryPolicy | None = None,
                 sleep: Callable[[float], None] = time.sleep):
        if cfg.mode == "inline":
            raise ValueError(
                "mode='inline' means no pool — use FrontendPool.from_config")
        self.cfg = cfg
        self.n_workers = int(cfg.workers)
        self.metrics = metrics
        self.tracer = tracer
        from deepdfa_tpu.pipeline import vocab_content_hash

        self.vocab_hash = vocab_content_hash(vocabs)
        self._factory = encode_session_factory(
            vocabs, cfg, vocab_source=vocab_source)
        self._spawn_policy = spawn_policy or RetryPolicy(
            attempts=3, base_delay=1.0, max_delay=15.0)
        self._attempts = attempts_per_item
        self._sleep = sleep
        self._queues: list[deque] = [deque() for _ in range(self.n_workers)]
        self._overflow: deque = deque()  # crash-requeued in-flight items
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._threads: list[threading.Thread] = []
        self._prespawned: dict[int, object] = {}
        self._started = False
        self._stopping = False
        self._rr = 0  # round-robin submit cursor
        self._depth = 0  # tasks queued, not yet picked up
        self._alive = 0
        self._submitted = 0
        self._encoded = 0
        self._steals = 0
        self._requeued = 0
        self._restarts = 0
        self._quarantine: list[dict] = []
        self._crashed: list[int] = []
        # parent-side encode intervals (wall clock — the same clock the
        # batcher's dispatch intervals use), for the bench's
        # encode↔dispatch overlap measurement
        self._intervals: deque = deque(maxlen=4096)

    @classmethod
    def from_config(cls, vocabs, cfg, **kwargs) -> "FrontendPool | None":
        """None when the config says inline — the caller encodes inline
        and no pool machinery exists at all."""
        if cfg is None or cfg.mode == "inline":
            return None
        return cls(vocabs, cfg, **kwargs)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "FrontendPool":
        if self._started:
            return self
        if self.cfg.mode == "process":
            # eager spawn: every child's vocab-hash handshake is verified
            # BEFORE the pool accepts work — a mismatch fails serve
            # startup fast instead of degrading silently per request
            with self._lock:
                try:
                    for wid in range(self.n_workers):
                        self._prespawned[wid] = self._factory(wid)
                except BaseException:
                    for sess in self._prespawned.values():
                        try:
                            sess.close()
                        except Exception:  # noqa: BLE001 — teardown best effort
                            pass
                    self._prespawned.clear()
                    raise
        self._threads = [
            threading.Thread(target=self._worker, args=(wid,),
                             name=f"frontend-{wid}", daemon=True)
            for wid in range(self.n_workers)
        ]
        with self._lock:
            self._alive = self.n_workers
            self._started = True
        for t in self._threads:
            t.start()
        return self

    def stop(self, drain: bool = True, timeout: float | None = None) -> None:
        """Refuse new submissions (flag-only — invariants 6/12); with
        ``drain`` let workers finish what's queued, else fail the queued
        futures immediately so callers fall back to inline encode."""
        with self._wake:
            self._stopping = True
            pending = [] if drain else self._drain_all_locked()
            if not drain:
                self._depth = 0
                if self.metrics is not None:
                    self.metrics.set_gauge("frontend_queue_depth", 0)
            self._wake.notify_all()
        for task in pending:
            self._complete(task, error=RuntimeError(
                "frontend pool shutting down"))
        deadline = None if timeout is None else time.monotonic() + timeout
        for t in self._threads:
            remain = (None if deadline is None
                      else max(0.0, deadline - time.monotonic()))
            t.join(timeout=remain)

    @property
    def alive(self) -> bool:
        with self._lock:
            return self._started and not self._stopping and self._alive > 0

    def queue_depth(self) -> int:
        with self._lock:
            return self._depth

    def signals(self) -> dict:
        """The overload-signal surface the admission layer, autoscaler
        and ``/healthz`` all read (ISSUE 18: one consistent surface):
        live queue depth, the queue-wait reservoir p99, and liveness."""
        return {
            "queue_depth": self.queue_depth(),
            "queue_wait_p99_ms": (
                self.metrics.frontend_queue_wait.quantile(0.99)
                if self.metrics is not None else None),
            "alive": self.alive,
        }

    def encode_intervals(self) -> list[tuple[float, float]]:
        """Wall-clock ``(start, end)`` per completed encode — the bench
        intersects these with the batcher's dispatch intervals to measure
        the encode↔dispatch overlap fraction."""
        with self._lock:
            return list(self._intervals)

    # -- client side --------------------------------------------------------

    def submit(self, source: str, key=None) -> Future:
        """Enqueue one raw source; the Future resolves to its encoded
        functions. Raises :class:`QueueFullError` (backpressure) or
        RuntimeError (draining / no live workers) — the server converts
        both into inline encode, never a 5xx."""
        from deepdfa_tpu.pipeline import source_key

        task = _FrontendTask(key if key is not None else source_key(source),
                             source,
                             self.tracer.current()
                             if self.tracer is not None else None)
        with self._wake:
            if not self._started or self._stopping:
                raise RuntimeError("frontend pool is not accepting work")
            if self._alive == 0:
                raise RuntimeError("frontend pool has no live workers")
            if self._depth >= self.cfg.max_queue:
                raise QueueFullError(
                    f"frontend queue at capacity ({self.cfg.max_queue})")
            self._queues[self._rr % self.n_workers].append(task)
            self._rr += 1
            self._depth += 1
            self._submitted += 1
            if self.metrics is not None:
                self.metrics.set_gauge("frontend_queue_depth", self._depth)
            self._wake.notify_all()
        return task.future

    # -- the work deque -----------------------------------------------------

    def _pop_task_locked(self, worker_id: int):
        """``(task, stolen)`` — own queue first, the shared overflow next,
        then steal from the back of the longest other queue (caller holds
        the lock; counters stay with the caller so every mutation sits
        lexically under its guard)."""
        try:
            return self._queues[worker_id].popleft(), False
        except IndexError:
            pass
        try:
            return self._overflow.popleft(), False
        except IndexError:
            pass
        victims = sorted(
            (i for i in range(self.n_workers) if i != worker_id),
            key=lambda i: -len(self._queues[i]))
        for i in victims:
            try:
                # steal cold work from the back
                return self._queues[i].pop(), True
            except IndexError:
                continue
        return None, False

    def _next_task(self, worker_id: int):
        with self._wake:
            while True:
                task, stolen = self._pop_task_locked(worker_id)
                if task is not None:
                    if stolen:
                        self._steals += 1
                    self._depth -= 1
                    if self.metrics is not None:
                        self.metrics.set_gauge(
                            "frontend_queue_depth", self._depth)
                    return task
                if self._stopping:
                    return None
                self._wake.wait()

    def _requeue(self, task, worker_id: int) -> None:
        with self._wake:
            self._overflow.append(task)
            self._depth += 1
            self._requeued += 1
            if self.metrics is not None:
                self.metrics.set_gauge("frontend_queue_depth", self._depth)
            self._wake.notify_all()
        logger.warning("frontend worker %d re-queued in-flight item %r",
                       worker_id, task.key)

    def _drain_all_locked(self) -> list:
        """Pop everything queued (caller holds the lock and owns the
        ``_depth`` reset, so the counter mutation sits under its guard)."""
        out = []
        for q in (*self._queues, self._overflow):
            while True:
                try:
                    out.append(q.popleft())
                except IndexError:
                    break
        return out

    # -- per-item processing ------------------------------------------------

    def _complete(self, task, result=None, error=None) -> None:
        with self._lock:
            if task.done:  # exactly-once guard (chaos-pinned, invariant 23)
                raise RuntimeError(
                    f"frontend task {task.key!r} completed twice — the "
                    "re-queue path double-counted an in-flight item")
            task.done = True
        if error is not None:
            task.future.set_exception(error)
        else:
            task.future.set_result(result)

    def _process(self, worker_id: int, sup: ExtractionSupervisor,
                 task) -> None:
        mono0, wall0 = time.monotonic(), time.time()
        wait_ms = (mono0 - task.submitted_mono) * 1e3
        if self.metrics is not None:
            self.metrics.frontend_queue_wait.observe(wait_ms)
        try:
            encoded = sup.run(
                task.key, lambda session: session.encode(task.source))
        except Exception as exc:  # noqa: BLE001 — classified by the caller
            self._complete(task, error=exc)
            return
        mono1, wall1 = time.monotonic(), time.time()
        with self._lock:
            self._encoded += 1
            self._intervals.append((wall0, wall1))
        if self.metrics is not None:
            self.metrics.frontend_encode.observe((mono1 - mono0) * 1e3)
        if self.tracer is not None:
            self.tracer.record(
                "frontend.encode", wall0, wall1, parent=task.ctx,
                worker=worker_id, n_functions=len(encoded),
                queue_wait_ms=round(wait_ms, 3))
        self._complete(task, result=encoded)

    # -- worker lifecycle ---------------------------------------------------

    def _supervisor(self, worker_id: int) -> ExtractionSupervisor:
        def factory():
            with self._lock:
                sess = self._prespawned.pop(worker_id, None)
            return sess if sess is not None else self._factory(worker_id)

        return ExtractionSupervisor(
            factory,
            spawn_policy=self._spawn_policy,
            attempts_per_item=self._attempts,
            sleep=self._sleep,
        )

    def _worker_loop(self, worker_id: int,
                     sup: ExtractionSupervisor) -> None:
        while True:
            task = self._next_task(worker_id)
            if task is None:
                return
            if faults.fire("frontend.worker_crash"):
                self._requeue(task, worker_id)
                raise _FrontendWorkerCrashed(worker_id)
            self._process(worker_id, sup, task)

    def _worker(self, worker_id: int) -> None:
        sup = self._supervisor(worker_id)
        try:
            self._worker_loop(worker_id, sup)
        except _FrontendWorkerCrashed:
            with self._lock:
                self._crashed.append(worker_id)
            logger.warning("frontend worker %d crashed; its queue will be "
                           "stolen by survivors", worker_id)
        finally:
            with self._lock:
                self._restarts += sup.restarts
                self._quarantine.extend(sup.quarantine)
            sup.close()
            self._on_worker_exit(worker_id)

    def _on_worker_exit(self, worker_id: int) -> None:
        with self._wake:
            self._alive -= 1
            # pool death with work still queued: fail the pending futures
            # so waiting requests fall back to inline encode — the queue
            # must never strand a request (invariant 25)
            fail: list = []
            if self._alive == 0:
                fail = self._drain_all_locked()
                self._depth = 0
                if self.metrics is not None:
                    self.metrics.set_gauge("frontend_queue_depth", 0)
            self._wake.notify_all()
        for task in fail:
            self._complete(task, error=RuntimeError(
                "frontend pool died — no live encode workers"))

    def report(self) -> dict:
        with self._lock:
            return {
                "mode": self.cfg.mode,
                "workers": self.n_workers,
                "alive": self._alive,
                "queue_depth": self._depth,
                "submitted": self._submitted,
                "encoded": self._encoded,
                "steals": self._steals,
                "requeued": self._requeued,
                "restarts": self._restarts,
                "quarantined": list(self._quarantine),
                "crashed_workers": list(self._crashed),
                "vocab_hash": self.vocab_hash,
            }
