"""Two-tier scoring cascade: borderline-band escalation to the joint model.

ROADMAP direction 3 (the MSIVD serving shape): tier 1 — the cheap GGNN
:class:`~deepdfa_tpu.serve.engine.ScoringEngine` — answers **every** request;
scores inside the configured borderline band ``[band_lo, band_hi]`` escalate
to tier 2, a second bounded micro-batch queue feeding the joint LLM+GNN
:class:`~deepdfa_tpu.llm.joint_engine.JointEngine`. One expensive LLM replica
thereby backs thousands of GGNN QPS: traffic outside the band (where the
GGNN is confident) never touches the LLM.

The degradation contract is standing **invariant 24**: tier-2 failure —
queue at capacity, deadline blown, engine raise, or an armed
``cascade.tier2_timeout`` / ``cascade.escalation_drop`` fault — may never
fail a request tier 1 already answered. The server keeps the tier-1 score,
marks the row ``tier2_degraded: true``, bumps
``deepdfa_serve_cascade_degraded_total``, and stays 200 with a green
``/healthz``. Escalations are journaled through the tracer
(``cascade.escalate`` → ``tier2.queue.wait`` → ``tier2.engine.dispatch``
spans), the per-tier latency reservoirs, and the cascade counters — band
routing is observable from the first request.

Queue policy mirrors :class:`~deepdfa_tpu.serve.batcher.MicroBatcher`
(size-or-deadline window, bounded depth, single dispatcher thread, per-batch
failure domain via futures) but is its own class: tier-2 items are
``(source_text, graph)`` pairs — the LLM branch tokenizes raw source, which
the tier-1 path never carries — and backpressure here means *degrade*, not
503.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field

from deepdfa_tpu.resilience import faults

__all__ = [
    "Tier2QueueFull",
    "Tier2DeadlineError",
    "EscalationDropped",
    "Tier2Batcher",
    "CascadeRouter",
]


class Tier2QueueFull(RuntimeError):
    """Tier-2 admission control: the bounded escalation queue is at
    capacity. The server degrades to the tier-1 answer — never a 503."""


class Tier2DeadlineError(RuntimeError):
    """The tier-2 deadline budget was blown (or ``cascade.tier2_timeout``
    fired). The tier-1 answer stands."""


class EscalationDropped(RuntimeError):
    """``cascade.escalation_drop`` fired at enqueue: the escalation is
    dropped, the request keeps its tier-1 answer."""


@dataclass
class _Escalation:
    text: str
    graph: object
    future: Future = field(default_factory=Future)
    ctx: object = None  # submitting request's span context (tracing handoff)
    enqueued_s: float = 0.0


class Tier2Batcher:
    """Bounded size-or-deadline micro-batch queue over a
    :class:`~deepdfa_tpu.llm.joint_engine.JointEngine`.

    One dispatcher thread (the joint engine serialises on the device
    anyway); engine failures fail that window's futures and the loop
    continues — a poisoned escalation must never kill tier 2, and tier-2
    death must never fail tier 1 (invariant 24: the server converts every
    future failure into a degraded tier-1 answer).
    """

    def __init__(self, engine, max_batch: int = 4, max_wait_ms: float = 10.0,
                 max_queue: int = 64, metrics=None, tracer=None):
        self.engine = engine
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_ms) / 1000.0
        self.max_queue = int(max_queue)
        self.metrics = metrics
        self.tracer = tracer
        self._pending: list[_Escalation] = []
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._stopping = False
        self._thread = threading.Thread(
            target=self._run, name="serve-tier2", daemon=True)
        self._started = False

    # -- client side --------------------------------------------------------

    def start(self) -> "Tier2Batcher":
        if not self._started:
            self._started = True
            self._thread.start()
        return self

    def submit(self, text: str, graph) -> Future:
        """Enqueue one borderline function; the Future resolves to its
        tier-2 probability. Raises :class:`Tier2QueueFull` (the caller
        degrades) or RuntimeError once draining."""
        item = _Escalation(text=text, graph=graph,
                           ctx=(self.tracer.current()
                                if self.tracer is not None else None),
                           enqueued_s=time.time())
        with self._wake:
            if self._stopping:
                raise RuntimeError("tier-2 batcher is draining")
            if len(self._pending) >= self.max_queue:
                raise Tier2QueueFull(
                    f"tier-2 queue at capacity ({self.max_queue})")
            self._pending.append(item)
            if self.metrics is not None:
                self.metrics.set_gauge("tier2_queue_depth",
                                       len(self._pending))
            self._wake.notify_all()
        return item.future

    def stop(self, drain: bool = True, timeout: float | None = None) -> None:
        with self._wake:
            self._stopping = True
            if not drain:
                for item in self._pending:
                    item.future.set_exception(
                        RuntimeError("server shutting down"))
                self._pending.clear()
            self._wake.notify_all()
        if self._started:
            self._thread.join(timeout=timeout)

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._pending)

    # -- dispatcher side ----------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._wake:
                while not self._pending and not self._stopping:
                    self._wake.wait()
                if not self._pending and self._stopping:
                    return
            deadline = time.monotonic() + self.max_wait_s
            with self._wake:
                while (len(self._pending) < self.max_batch
                       and not self._stopping):
                    remain = deadline - time.monotonic()
                    if remain <= 0:
                        break
                    self._wake.wait(timeout=remain)
                window, self._pending = (
                    self._pending[:self.max_batch],
                    self._pending[self.max_batch:],
                )
                if self.metrics is not None:
                    self.metrics.set_gauge("tier2_queue_depth",
                                           len(self._pending))
            self._dispatch(window)

    def _dispatch(self, window: list[_Escalation]) -> None:
        tracer, now = self.tracer, time.time()
        first_ctx = next((i.ctx for i in window if i.ctx is not None), None)
        for item in window:
            if item.enqueued_s:
                if self.metrics is not None:
                    self.metrics.tier2_queue_wait.observe(
                        (now - item.enqueued_s) * 1e3)
                if tracer is not None:
                    tracer.record("tier2.queue.wait", item.enqueued_s, now,
                                  parent=item.ctx)
        t0 = time.time()
        try:
            # armed chaos: treat this window's deadline as blown — the
            # requests must keep their tier-1 answers (invariant 24)
            if faults.fire("cascade.tier2_timeout"):
                raise Tier2DeadlineError(
                    "injected tier-2 deadline blow (cascade.tier2_timeout)")
            probs = self.engine.score([(i.text, i.graph) for i in window])
        except Exception as exc:  # noqa: BLE001 — per-window failure domain
            if tracer is not None:
                tracer.record("tier2.engine.dispatch", t0, parent=first_ctx,
                              n_items=len(window),
                              error=type(exc).__name__)
            for item in window:
                item.future.set_exception(exc)
            return
        t1 = time.time()
        if self.metrics is not None:
            self.metrics.tier2_dispatch.observe((t1 - t0) * 1e3)
        if tracer is not None:
            tracer.record("tier2.engine.dispatch", t0, t1, parent=first_ctx,
                          n_items=len(window))
        for item, p in zip(window, probs):
            item.future.set_result(float(p))


class CascadeRouter:
    """Band routing + the tier-2 queue, packaged for the server.

    ``escalate`` enqueues; the *caller* owns the wait (``deadline_s``) and
    the degradation decision, because only the caller holds the tier-1
    answer to fall back on.
    """

    def __init__(self, cfg, engine, metrics=None, tracer=None):
        self.cfg = cfg
        self.engine = engine
        self.metrics = metrics
        self.tracer = tracer
        self.deadline_s = float(cfg.tier2_deadline_ms) / 1000.0
        self.batcher = Tier2Batcher(
            engine,
            max_batch=cfg.tier2_max_batch,
            max_wait_ms=cfg.tier2_max_wait_ms,
            max_queue=cfg.tier2_max_queue,
            metrics=metrics,
            tracer=tracer,
        )
        self.model_rev = getattr(engine, "model_rev", "unknown")

    def start(self) -> "CascadeRouter":
        self.batcher.start()
        return self

    def stop(self, drain: bool = True, timeout: float | None = None) -> None:
        self.batcher.stop(drain=drain, timeout=timeout)

    def in_band(self, prob: float) -> bool:
        return self.cfg.band_lo <= prob <= self.cfg.band_hi

    def escalation_allowed(self, brownout_level: int = 0) -> bool:
        """Brownout level >= 2 is tier-1 only (serve/admission.py): the
        request keeps its tier-1 answer — degradation, never a 5xx — but
        no tier-2 capacity is spent while the fleet sheds load."""
        from .admission import BROWNOUT_TIER1_ONLY

        return brownout_level < BROWNOUT_TIER1_ONLY

    def escalate(self, text: str, graph) -> Future:
        """Enqueue one borderline function for tier-2 rescoring. Raises
        :class:`EscalationDropped` (armed ``cascade.escalation_drop``) or
        :class:`Tier2QueueFull` — both mean: keep the tier-1 answer."""
        if faults.fire("cascade.escalation_drop"):
            raise EscalationDropped(
                "injected escalation drop (cascade.escalation_drop)")
        return self.batcher.submit(text, graph)
