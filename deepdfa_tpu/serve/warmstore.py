"""Warm-start store: content-addressed compiled bucket artifacts.

Every replica that joins a serving fleet today pays a full cold compile
of the bucket ladder before it can take traffic. The store removes that
tax: the FIRST engine to compile a bucket exports the compiled program
(StableHLO via the same ``jax.export`` path :mod:`deepdfa_tpu.serving`
uses) and commits it here under a content address derived from everything
that determines the program — vocab hash, model revision (a content hash
of the parameters), precision, label style, feature keys, and the
bucket's padded shape. A joining replica whose key matches loads the
serialized program instead of re-tracing/re-lowering the model; the
difference is journaled as ``compile_seconds_saved``.

Commit protocol mirrors the checkpoint invariant (ROADMAP resilience #1):
the payload lands first, then the ``.json`` meta commits via one
``os.replace`` — an entry EXISTS iff its meta parses, so a ``kill -9``
mid-put costs a re-compile, never a torn artifact. Keys are shared-
nothing across model revisions: a new checkpoint hashes to new keys and
old entries simply stop being read (GC is an ``ls``-and-unlink away, the
store never mutates an entry in place).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from pathlib import Path

from deepdfa_tpu.resilience.journal import atomic_write_text

__all__ = ["WarmEntry", "WarmStore", "bucket_artifact_key"]


def bucket_artifact_key(vocab_hash: str | None, model_rev: str | None,
                        precision: str, label_style: str, feat_keys,
                        max_graphs: int, max_nodes: int,
                        max_edges: int) -> str:
    """Content address of one bucket's compiled program. Everything that
    changes the lowered module must be in the key — two replicas agree on
    a key exactly when the loaded program is bit-for-bit usable."""
    payload = "|".join([
        vocab_hash or "novocab", model_rev or "norev", precision,
        label_style, ",".join(feat_keys),
        f"{max_graphs}x{max_nodes}x{max_edges}",
    ])
    return hashlib.sha256(payload.encode()).hexdigest()[:24]


@dataclasses.dataclass(frozen=True)
class WarmEntry:
    """One committed artifact: the serialized exported program plus the
    meta the populating replica recorded (``compile_seconds`` is what a
    loader saves by not compiling)."""

    key: str
    payload: bytes
    meta: dict


class WarmStore:
    """Directory of ``{key}.stablehlo`` + ``{key}.json`` pairs. The meta
    json is the commit marker (written last, atomically); ``get`` treats
    anything without a parseable meta as absent."""

    def __init__(self, root: str | Path):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    def _payload_path(self, key: str) -> Path:
        return self.root / f"{key}.stablehlo"

    def _meta_path(self, key: str) -> Path:
        return self.root / f"{key}.json"

    def get(self, key: str) -> WarmEntry | None:
        try:
            meta = json.loads(self._meta_path(key).read_text())
            payload = self._payload_path(key).read_bytes()
        except (FileNotFoundError, OSError, json.JSONDecodeError):
            return None
        if not isinstance(meta, dict):
            return None
        return WarmEntry(key=key, payload=payload, meta=meta)

    def put(self, key: str, payload: bytes, meta: dict) -> WarmEntry:
        """Commit an artifact: payload sideways + replace, THEN the meta —
        a reader that sees the meta is guaranteed a whole payload."""
        ppath = self._payload_path(key)
        tmp = ppath.with_name(ppath.name + ".tmp")
        tmp.write_bytes(payload)
        import os

        os.replace(tmp, ppath)
        atomic_write_text(self._meta_path(key), json.dumps(meta, indent=2,
                                                           sort_keys=True))
        return WarmEntry(key=key, payload=payload, meta=dict(meta))

    def keys(self) -> list[str]:
        """Committed keys only (meta present and parseable)."""
        out = []
        for p in sorted(self.root.glob("*.json")):
            key = p.stem
            if self.get(key) is not None:
                out.append(key)
        return out

    def stats(self) -> dict:
        keys = self.keys()
        return {
            "entries": len(keys),
            "bytes": sum(self._payload_path(k).stat().st_size for k in keys),
        }
