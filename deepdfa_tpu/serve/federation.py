"""Multi-cell federation: spillover routing, cell-level drain, and
cell-kill survival.

One autoscaled fleet (a :class:`~deepdfa_tpu.serve.router.FleetRouter`
plus its :class:`~deepdfa_tpu.serve.autoscaler.Autoscaler`) is still one
blast radius. The :class:`FederationRouter` composes the PR 7/12
membership machinery one level up: it fronts N shared-nothing **cells**,
each a complete fleet with its own router, replicas, warm store, and
admission plane. Capacity grows by adding cells; robustness comes from
routing between them, never from any cell being reliable (invariant
candidate 32: losing any single cell loses no request).

Routing is **source-key sticky** by default — the same consistent-hash
ring as the fleet router, so each source's scan-cache entry lives in
exactly one cell and cache capital is never duplicated across cells.
Stickiness yields only under pressure:

- **spillover** — a cell that reports saturation (its ``/healthz``
  ``brownout_level``, its frontend queue-wait p99, or its ``/slo``
  fast-window burn past the configured watermarks — no new probes, the
  cell already tells the truth) keeps its ring position but new requests
  prefer the least-burned healthy cell until it recovers;
- **cell-level drain** — a deploy drains a whole cell flag-only: the
  cell leaves the federation ring FIRST (no new forwards), in-flight
  forwards finish inside :data:`FederationConfig.drain_deadline_s`, then
  the cell's own router gets the drain flag (the invariant 6/12/22 shape
  one level up); undrain readmits it through the same readiness gate as
  a new member;
- **cell-death failover** — a forward that fails at the socket marks the
  cell down and retries the next cell; a dead cell costs its cache
  shard, never its keyspace's availability, and nothing is converted to
  a 5xx;
- **cross-cell shed semantics** — a 429 from one cell triggers
  spillover; only a FLEET-WIDE shed (every reachable cell shed) surfaces
  to the client, still as 429 + the max Retry-After any cell advertised,
  never a 5xx (invariant 30 one level up). When no cell is reachable at
  all the client gets 429 + ``retry_after_floor_s`` — scoring is
  idempotent, so explicit backpressure beats a lying 5xx.

Chaos points: ``federation.cell_kill`` (the probe loop kill -9s a whole
cell through the installed ``kill_hook``), ``federation.spillover_drop``
(a spilled forward dies on the wire — counted, retried, never a 5xx),
``federation.probe_partition`` (one health probe reads as a socket
failure — the cell is marked down and rejoins on the next clean probe).

Entry point: ``python -m deepdfa_tpu.serve.federation --cell HOST:PORT
...``; load-test with ``scripts/bench_serving.py --federation N`` (the
cell-killed sawtooth).
"""

from __future__ import annotations

import http.client
import json
import logging
import signal
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from deepdfa_tpu.config import FederationConfig, ObsConfig
from deepdfa_tpu.obs import MetricsRegistry, SLOEngine, federation_specs
from deepdfa_tpu.pipeline import source_key
from deepdfa_tpu.resilience import faults

from .autoscaler import max_fast_burn
from .metrics import LatencyReservoir
from .router import FORWARD_TIMEOUT_S, HashRing

__all__ = ["Cell", "FederationMetrics", "FederationRouter", "main"]

logger = logging.getLogger(__name__)

PROBE_TIMEOUT_S = 5.0


@dataclass
class Cell:
    """One fleet the federation fronts. ``state`` transitions mirror
    :class:`~deepdfa_tpu.serve.router.Backend` one level up:
    pending → ready (first healthy probe) → draining/down → ready."""

    name: str                     # "host:port" of the cell's FleetRouter
    host: str
    port: int
    state: str = "pending"
    health: dict = field(default_factory=dict)  # last /healthz body
    burn: float | None = None     # last /slo fast-window burn rate
    forwarded: int = 0
    failures: int = 0
    spillover: int = 0            # forwards this cell absorbed for others
    inflight: int = 0             # forwards currently on the wire

    @classmethod
    def parse(cls, spec: str) -> "Cell":
        host, _, port = spec.rpartition(":")
        return cls(name=spec, host=host or "127.0.0.1", port=int(port))


class FederationMetrics:
    """Federation-tier counters; rendered as ``deepdfa_federation_*``."""

    def __init__(self, latency_window: int = 2048):
        self._lock = threading.Lock()
        self.requests_total = 0
        self.forwarded_total: dict[str, int] = {}
        self.spillover_total = 0
        self.spillover_errors_total = 0
        self.retries_total = 0
        self.fleetwide_shed_total = 0
        self.fleetwide_5xx_total = 0
        self.no_cell_total = 0
        self.latency = LatencyReservoir(latency_window)

    def inc(self, name: str, by: int = 1) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + by)

    def observe_forward(self, cell: str) -> None:
        with self._lock:
            self.forwarded_total[cell] = self.forwarded_total.get(cell, 0) + 1

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "requests_total": self.requests_total,
                "forwarded_total": dict(self.forwarded_total),
                "spillover_total": self.spillover_total,
                "spillover_errors_total": self.spillover_errors_total,
                "retries_total": self.retries_total,
                "fleetwide_shed_total": self.fleetwide_shed_total,
                "fleetwide_5xx_total": self.fleetwide_5xx_total,
                "no_cell_total": self.no_cell_total,
                "latency_p50_ms": self.latency.quantile(0.50),
                "latency_p99_ms": self.latency.quantile(0.99),
            }

    def render(self) -> str:
        snap = self.snapshot()
        reg = MetricsRegistry("deepdfa_federation_")
        reg.counter("requests_total",
                    "Every /score the federation received").set(
            snap["requests_total"])
        fwd = reg.counter("forwarded_total", "Forwards by cell",
                          labels=("cell",))
        for name, n in snap["forwarded_total"].items():
            fwd.set(n, cell=name)
        reg.counter("spillover_total",
                    "Forwards served off the sticky cell").set(
            snap["spillover_total"])
        reg.counter("spillover_errors_total",
                    "Spilled forwards lost on the wire (retried)").set(
            snap["spillover_errors_total"])
        reg.counter("retries_total",
                    "Per-request failovers past a cell").set(
            snap["retries_total"])
        reg.counter("fleetwide_shed_total",
                    "Requests every reachable cell shed (client 429)").set(
            snap["fleetwide_shed_total"])
        reg.counter("fleetwide_5xx_total",
                    "5xx leaked to a client (invariant 32 violations)").set(
            snap["fleetwide_5xx_total"])
        reg.counter("no_cell_total",
                    "Requests with no reachable cell (client 429)").set(
            snap["no_cell_total"])
        lat = reg.gauge("latency_ms",
                        "Federation round-trip latency",
                        labels=("quantile",))
        for q in (0.50, 0.99):
            lat.set(self.latency.quantile(q), quantile=q)
        return reg.render()


class FederationRouter:
    """The federation's one client-facing surface.

    ``POST /score`` routes the body's ``source_key`` sticky on the cell
    ring, spills past saturated/dead/shedding cells, and proxies the
    first successful cell response verbatim (plus an ``X-DeepDFA-Cell``
    header). ``GET /healthz`` reports the cell table, ``GET /metrics``
    the ``deepdfa_federation_*`` counters, ``GET /slo`` the federation
    objectives; ``/admin/cells`` is the membership + drain surface."""

    def __init__(self, cells=(), cfg: FederationConfig | None = None,
                 host: str = "127.0.0.1", port: int = 0,
                 metrics: FederationMetrics | None = None,
                 obs: ObsConfig | None = None,
                 kill_hook=None):
        self.cfg = cfg or FederationConfig()
        self._cells_lock = threading.Lock()
        self.cells: dict[str, Cell] = {}
        for spec in tuple(self.cfg.cells) + tuple(cells):
            c = spec if isinstance(spec, Cell) else Cell.parse(str(spec))
            self.cells.setdefault(c.name, c)
        self.ring = HashRing(self.cfg.vnodes)
        self.metrics = metrics or FederationMetrics()
        obs = obs or ObsConfig()
        self.slo = SLOEngine(
            federation_specs(availability=obs.slo_availability,
                             p99_ms=obs.slo_p99_ms),
            fast_window_s=obs.slo_fast_window_s,
            slow_window_s=obs.slo_slow_window_s,
            burn_threshold=obs.slo_burn_threshold)
        # chaos surface: federation.cell_kill fires through this hook —
        # the harness (bench/test) owns the processes, the router only
        # names the victim (the autoscale.replica_crash shape)
        self.kill_hook = kill_hook
        self._draining = threading.Event()
        self._stop_requested = threading.Event()
        self._probe_thread: threading.Thread | None = None
        self.httpd = ThreadingHTTPServer((host, port), _make_handler(self))
        self.httpd.daemon_threads = True
        self._serve_thread: threading.Thread | None = None

    # -- lifecycle ----------------------------------------------------------

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    @property
    def draining(self) -> bool:
        return self._draining.is_set() or self._stop_requested.is_set()

    def start(self, probe: bool = True) -> "FederationRouter":
        if probe:
            self.probe_once()
            self._probe_thread = threading.Thread(
                target=self._probe_loop, name="federation-probe", daemon=True)
            self._probe_thread.start()
        self._serve_thread = threading.Thread(
            target=self.httpd.serve_forever, name="federation-http",
            daemon=True)
        self._serve_thread.start()
        logger.info("federating on :%s over %d cell(s), %d ready",
                    self.port, len(self._cell_list()), len(self.ring))
        return self

    def install_signal_handlers(self) -> None:
        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, lambda *_: self._stop_requested.set())

    def wait(self) -> dict:
        while not self._stop_requested.wait(timeout=0.2):
            pass
        return self.shutdown()

    def request_stop(self) -> None:
        self._stop_requested.set()

    def shutdown(self) -> dict:
        self._draining.set()
        self._stop_requested.set()
        self.httpd.shutdown()
        self.httpd.server_close()
        return self.metrics.snapshot()

    def render_slo(self) -> str:
        self.slo.observe(self.metrics.snapshot())
        return self.slo.render("deepdfa_federation_")

    # -- cell membership ----------------------------------------------------

    def add_cell(self, spec) -> Cell:
        """Register a cell at runtime. It enters ``pending`` and joins the
        ring only after a healthy probe — the same readiness gate as the
        fleet router's backends (invariant 13), so a cell whose fleet is
        still compiling takes no federation traffic."""
        c = spec if isinstance(spec, Cell) else Cell.parse(str(spec))
        with self._cells_lock:
            existing = self.cells.get(c.name)
            if existing is not None:
                return existing
            self.cells[c.name] = c
        self._probe_cell(c)
        logger.info("cell %s registered (state %s)", c.name, c.state)
        return c

    def remove_cell(self, name: str) -> bool:
        with self._cells_lock:
            c = self.cells.pop(name, None)
        if c is None:
            return False
        self.ring.remove(name)
        logger.info("cell %s deregistered", name)
        return True

    def drain_cell(self, name: str) -> tuple[bool, dict]:
        """Cell-level drain for deploys, in invariant-6 order: (1) the
        cell leaves the federation ring — no NEW forwards route to it;
        (2) in-flight forwards finish (bounded by ``drain_deadline_s``);
        (3) the cell's own router gets the flag-only drain, which
        cascades to its replicas through its own probe loop."""
        c = self._get_cell(name)
        if c is None:
            return False, {"error": f"no cell {name}"}
        self.ring.remove(name)
        c.state = "draining"
        deadline = time.monotonic() + self.cfg.drain_deadline_s
        while c.inflight > 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        inflight_left = c.inflight
        try:
            status, body = self._cell_admin(c, {"action": "drain"})
        except OSError as exc:
            status, body = 0, {"error": f"{type(exc).__name__}: {exc}"}
        logger.info("cell %s drained (inflight_left=%d, cell said %s)",
                    name, inflight_left, status)
        return True, {"cell": name, "state": c.state,
                      "inflight_at_flag": inflight_left,
                      "cell_status": status, "cell_body": body}

    def undrain_cell(self, name: str) -> tuple[bool, dict]:
        """Reverse a cell drain: clear the cell router's flag, then let
        the next probe readmit it through the readiness gate."""
        c = self._get_cell(name)
        if c is None:
            return False, {"error": f"no cell {name}"}
        try:
            status, body = self._cell_admin(c, {"action": "undrain"})
        except OSError as exc:
            return False, {"error": f"{type(exc).__name__}: {exc}"}
        self._probe_cell(c)
        return True, {"cell": name, "state": c.state,
                      "cell_status": status, "cell_body": body}

    def _cell_admin(self, c: Cell, payload: dict) -> tuple[int, dict]:
        conn = http.client.HTTPConnection(c.host, c.port,
                                          timeout=PROBE_TIMEOUT_S)
        try:
            conn.request("POST", "/admin/drain", body=json.dumps(payload),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            body = json.loads(resp.read() or b"{}")
        finally:
            conn.close()
        return resp.status, body

    def _cell_list(self) -> list[Cell]:
        with self._cells_lock:
            return list(self.cells.values())

    def _get_cell(self, name: str) -> Cell | None:
        with self._cells_lock:
            return self.cells.get(name)

    # -- cell health --------------------------------------------------------

    def _probe_cell(self, c: Cell) -> None:
        try:
            if faults.fire("federation.probe_partition"):
                raise OSError("injected probe partition")
            conn = http.client.HTTPConnection(c.host, c.port,
                                              timeout=PROBE_TIMEOUT_S)
            try:
                conn.request("GET", "/healthz")
                resp = conn.getresponse()
                body = json.loads(resp.read() or b"{}")
            finally:
                conn.close()
        except (OSError, json.JSONDecodeError) as exc:
            self._mark(c, "down", {"error": f"{type(exc).__name__}: {exc}"})
            return
        if resp.status == 200 and not body.get("draining"):
            if body.get("warm", True):
                self._mark(c, "ready", body)
            else:
                self._mark(c, "pending", body)
        elif body.get("draining"):
            self._mark(c, "draining", body)
        else:
            self._mark(c, "down", body)
        if c.state == "ready":
            c.burn = self._probe_burn(c)

    def _probe_burn(self, c: Cell) -> float | None:
        """The cell's own ``/slo`` verdict — the spillover burn signal.
        A failed scrape is not a health event (the healthz probe owns
        liveness); the last burn just goes stale-to-None."""
        try:
            conn = http.client.HTTPConnection(c.host, c.port,
                                              timeout=PROBE_TIMEOUT_S)
            try:
                conn.request("GET", "/slo")
                resp = conn.getresponse()
                text = resp.read().decode("utf-8", "replace")
            finally:
                conn.close()
        except OSError:
            return None
        return max_fast_burn(text) if resp.status == 200 else None

    def _mark(self, c: Cell, state: str, health: dict) -> None:
        prev = c.state
        c.state = state
        c.health = health
        if state == "ready":
            self.ring.add(c.name)
        else:
            self.ring.remove(c.name)
        if state != prev:
            logger.info("cell %s: %s -> %s", c.name, prev, state)

    def probe_once(self) -> dict:
        """Probe every cell once; returns ``{name: state}``."""
        snapshot = self._cell_list()
        if self.kill_hook is not None and faults.fire("federation.cell_kill"):
            victim = next((c for c in snapshot if c.state == "ready"), None)
            if victim is not None:
                logger.warning("cell_kill fault: killing cell %s",
                               victim.name)
                self.kill_hook(victim.name)
        for c in snapshot:
            self._probe_cell(c)
        return {c.name: c.state for c in snapshot}

    def _probe_loop(self) -> None:
        while not self._stop_requested.wait(
                timeout=self.cfg.probe_interval_s):
            self.probe_once()

    def saturated(self, c: Cell) -> bool:
        """Derived, never stored: the cell's last probe already carries
        the truth (brownout level, queue-wait p99, SLO burn) — saturation
        is a judgment over it at routing time."""
        level = int(c.health.get("brownout_level") or 0)
        if level >= self.cfg.spill_brownout_level:
            return True
        queue_wait = float(c.health.get("frontend_queue_wait_p99_ms") or 0.0)
        if queue_wait >= self.cfg.spill_queue_wait_p99_ms:
            return True
        return c.burn is not None and c.burn >= self.cfg.spill_burn_high

    # -- request path -------------------------------------------------------

    def plan_route(self, key: str) -> list[str]:
        """The ordered cells one request will try. Sticky owner first —
        UNLESS it is saturated, in which case the least-burned healthy
        non-saturated cell leads and the sticky owner becomes the
        fallback (saturation spillover is a preference, not a refusal:
        when every cell is saturated the sticky owner still serves)."""
        ready = [c for c in self._cell_list() if c.state == "ready"
                 and c.name in self.ring.nodes]
        if not ready:
            return []
        by_name = {c.name: c for c in ready}
        sticky = self.ring.route(key)
        order = sorted(
            ready, key=lambda c: (self.saturated(c),
                                  c.burn if c.burn is not None else 0.0,
                                  c.name != (sticky or ""), c.name))
        if sticky in by_name and not self.saturated(by_name[sticky]):
            order = [by_name[sticky]] + [c for c in order
                                         if c.name != sticky]
        return [c.name for c in order]

    def handle_score(self, raw: bytes) -> tuple[int, dict, dict]:
        """Route + forward one ``/score`` body across the cell ring.
        Returns ``(status, body, extra_headers)`` — never a 5xx of the
        federation's own making (invariant candidate 32)."""
        if self.draining:
            # the federation front drains like a cell: explicit
            # backpressure, scoring is idempotent, the client retries
            return 429, {"error": "federation is draining",
                         "retry_after_s": self.cfg.retry_after_floor_s}, {
                "Retry-After": str(self.cfg.retry_after_floor_s)}
        try:
            payload = json.loads(raw or b"{}")
        except json.JSONDecodeError:
            return 400, {"error": "body is not valid JSON"}, {}
        source = payload.get("source") if isinstance(payload, dict) else None
        if not isinstance(source, str) or not source.strip():
            return 400, {"error": "body must be JSON with a 'source' "
                                  "string"}, {}
        key = source_key(source)
        plan = self.plan_route(key)
        # spillover is relative to the RING OWNER, not the plan position:
        # a saturation-reordered plan serving at hop 0 is still spillover
        # (the owner was demoted), while a request whose dead owner has
        # already left the ring is reassignment, not spillover
        owner = self.ring.route(key)
        max_retry_after = 0
        saw_shed = False
        for hop, name in enumerate(plan):
            c = self._get_cell(name)
            if c is None:  # deregistered between plan and lookup
                self.ring.remove(name)
                continue
            spill = owner is not None and name != owner
            try:
                if spill and faults.fire("federation.spillover_drop"):
                    raise OSError("injected spillover drop")
                status, body, retry_after = self._forward(c, raw)
            except OSError as exc:
                c.failures += 1
                self.metrics.inc("retries_total")
                if spill:
                    # a lost spillover forward is a counted error, not a
                    # health event — the next cell absorbs it
                    self.metrics.inc("spillover_errors_total")
                    logger.warning("spilled forward to %s lost (%s) — "
                                   "retrying next cell", name,
                                   type(exc).__name__)
                else:
                    self._mark(c, "down",
                               {"error": f"{type(exc).__name__}: {exc}"})
                    logger.warning("forward to cell %s failed (%s) — "
                                   "failing over", name, type(exc).__name__)
                continue
            if status == 429:
                # one cell shedding is spillover's cue, not the client's
                # problem — only a fleet-wide shed surfaces (invariant 30)
                saw_shed = True
                max_retry_after = max(max_retry_after, retry_after or 0)
                self.metrics.inc("retries_total")
                continue
            if status == 503 and "draining" in str(
                    (body or {}).get("error", "")):
                self._mark(c, "draining", {"error": body.get("error")})
                self.metrics.inc("retries_total")
                continue
            if status >= 500:
                # a cell-internal failure is tracked, never surfaced —
                # scoring is idempotent, the next cell re-scores
                c.failures += 1
                self.metrics.inc("retries_total")
                logger.warning("cell %s returned %d — failing over",
                               name, status)
                continue
            c.forwarded += 1
            if spill:
                c.spillover += 1
                self.metrics.inc("spillover_total")
            self.metrics.observe_forward(name)
            return status, body, {"X-DeepDFA-Cell": name,
                                  "X-DeepDFA-Spillover": str(spill).lower()}
        # exhausted: every reachable cell shed, or none was reachable.
        # Either way the honest answer is backpressure, never a 5xx.
        retry_after = max(max_retry_after, self.cfg.retry_after_floor_s)
        if saw_shed:
            self.metrics.inc("fleetwide_shed_total")
            error = "every cell shed this request"
        else:
            self.metrics.inc("no_cell_total")
            error = "no reachable cell" if plan else "no ready cell"
        return 429, {"error": error, "retry_after_s": retry_after}, {
            "Retry-After": str(int(retry_after))}

    def _forward(self, c: Cell,
                 raw: bytes) -> tuple[int, dict, int | None]:
        """One cell round-trip: ``(status, body, retry_after_s)`` — the
        Retry-After comes from the header the cell router propagates
        (falling back to the body the admission plane writes)."""
        c.inflight += 1
        try:
            conn = http.client.HTTPConnection(c.host, c.port,
                                              timeout=FORWARD_TIMEOUT_S)
            try:
                conn.request("POST", "/score", body=raw,
                             headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                data = resp.read()
            finally:
                conn.close()
        finally:
            c.inflight -= 1
        try:
            body = json.loads(data or b"{}")
        except json.JSONDecodeError:
            return 502, {"error": "cell returned invalid JSON"}, None
        retry_after = None
        header = resp.getheader("Retry-After")
        if header is not None:
            try:
                retry_after = int(header)
            except ValueError:
                retry_after = None
        if retry_after is None and isinstance(body, dict) \
                and body.get("retry_after_s") is not None:
            retry_after = int(body["retry_after_s"])
        return resp.status, body, retry_after

    # -- admin + health -----------------------------------------------------

    def admin_cells(self) -> tuple[int, dict]:
        """``GET /admin/cells``: the cell table as the operator sees it."""
        return 200, {
            "ready": sorted(self.ring.nodes),
            "cells": {c.name: {"state": c.state,
                               "saturated": (c.state == "ready"
                                             and self.saturated(c)),
                               "burn": c.burn,
                               "brownout_level": int(
                                   c.health.get("brownout_level") or 0),
                               "forwarded": c.forwarded,
                               "spillover": c.spillover,
                               "failures": c.failures}
                      for c in self._cell_list()},
        }

    def handle_admin(self, raw: bytes) -> tuple[int, dict]:
        """``POST /admin/cells``: ``{"action": "add"|"remove"|"drain"|
        "undrain", "cell": "host:port"}`` — the deploy surface. Add is
        readiness-gated; drain runs the invariant-6 order (ring exit
        first, in-flight forwards finish, then the cell's flag)."""
        try:
            payload = json.loads(raw or b"{}")
        except json.JSONDecodeError:
            return 400, {"error": "body is not valid JSON"}
        action = payload.get("action") if isinstance(payload, dict) else None
        spec = payload.get("cell") if isinstance(payload, dict) else None
        if action not in ("add", "remove", "drain", "undrain") \
                or not isinstance(spec, str) or ":" not in spec:
            return 400, {"error": "need {'action': 'add'|'remove'|'drain'|"
                                  "'undrain', 'cell': 'host:port'}"}
        if action == "add":
            c = self.add_cell(spec)
            return 200, {"cell": c.name, "state": c.state}
        if action == "remove":
            removed = self.remove_cell(spec)
            return (200 if removed else 404), {"cell": spec,
                                               "removed": removed}
        ok, body = (self.drain_cell(spec) if action == "drain"
                    else self.undrain_cell(spec))
        return (200 if ok else 404), body

    def healthz(self) -> tuple[int, dict]:
        ready = sorted(self.ring.nodes)
        body = {
            "status": "draining" if self.draining else (
                "ok" if ready else "no_ready_cells"),
            "draining": self.draining,
            "ready_cells": ready,
            "cells": {c.name: {"state": c.state,
                               "saturated": (c.state == "ready"
                                             and self.saturated(c)),
                               "burn": c.burn,
                               "brownout_level": int(
                                   c.health.get("brownout_level") or 0)}
                      for c in self._cell_list()},
        }
        ok = bool(ready) and not self.draining
        return (200 if ok else 503), body


def _make_handler(fed: FederationRouter):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):
            logger.debug("federation http: " + fmt, *args)

        def _send(self, code: int, body, headers=None,
                  content_type="application/json"):
            data = (body.encode() if isinstance(body, str)
                    else json.dumps(body).encode())
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            if self.path == "/healthz":
                code, body = fed.healthz()
                self._send(code, body)
            elif self.path == "/metrics":
                self._send(200, fed.metrics.render(),
                           content_type="text/plain; version=0.0.4")
            elif self.path == "/slo":
                self._send(200, fed.render_slo(),
                           content_type="text/plain; version=0.0.4")
            elif self.path == "/admin/cells":
                code, body = fed.admin_cells()
                self._send(code, body)
            else:
                self._send(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            if self.path == "/admin/cells":
                try:
                    length = int(self.headers.get("Content-Length") or 0)
                    code, body = fed.handle_admin(self.rfile.read(length))
                except Exception as exc:  # noqa: BLE001
                    code, body = 500, {
                        "error": f"{type(exc).__name__}: {exc}"}
                self._send(code, body)
                return
            if self.path != "/score":
                self._send(404, {"error": f"no route {self.path}"})
                return
            t0 = time.perf_counter()
            fed.metrics.inc("requests_total")
            try:
                length = int(self.headers.get("Content-Length") or 0)
                code, body, extra = fed.handle_score(self.rfile.read(length))
            except Exception as exc:  # noqa: BLE001 — request dies, the
                # federation front does not; this is the ONLY federation
                # path that can 5xx, and the counter indicts it
                code, body, extra = 500, {
                    "error": f"{type(exc).__name__}: {exc}"}, {}
            if code >= 500:
                fed.metrics.inc("fleetwide_5xx_total")
            self._send(code, body, headers=extra)
            fed.metrics.latency.observe((time.perf_counter() - t0) * 1000.0)

    return Handler


def main(argv=None) -> dict:
    import argparse

    parser = argparse.ArgumentParser(prog="deepdfa-tpu-federate")
    parser.add_argument("--cell", action="append", default=[],
                        dest="cells", metavar="HOST:PORT",
                        help="a cell's FleetRouter to front (repeatable)")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8950)
    parser.add_argument("--vnodes", type=int, default=16)
    parser.add_argument("--probe-interval", type=float, default=1.0,
                        dest="probe_interval_s")
    args = parser.parse_args(argv)
    if not args.cells:
        parser.error("need at least one --cell HOST:PORT")

    logging.basicConfig(level=logging.INFO)
    cfg = FederationConfig(enabled=True, cells=tuple(args.cells),
                           vnodes=args.vnodes,
                           probe_interval_s=args.probe_interval_s)
    fed = FederationRouter(cfg=cfg, host=args.host, port=args.port)
    fed.install_signal_handlers()
    fed.start()
    print(json.dumps({"status": "federating", "port": fed.port,
                      "cells": fed.probe_once()}), flush=True)
    summary = fed.wait()
    print(json.dumps({"status": "drained", **summary}), flush=True)
    return summary


if __name__ == "__main__":
    main()
