"""Admission control, QoS classes, and brownout mode — the serving
fleet's explicit overload behavior (ROADMAP direction 4(b)+(c)).

Three pieces, layered in front of the frontend encode pool so load is
shed *before* encode cost is paid:

- :class:`TokenBucket` — per-(tenant, class) refill buckets. The
  Retry-After a shed request carries is derived from the bucket's refill
  state (the ceil of the token deficit over the refill rate), a pure
  function of bucket state — never wall-clock randomness (invariant 5).
- :class:`AdmissionController` — the per-request admit/shed decision:
  two priority classes (``interactive`` score vs ``batch`` rescore,
  tagged per-request), deadline-aware shedding off the frontend
  queue-wait p99 and queue-depth signals, and the brownout level. A shed
  is ALWAYS a 429 + deterministic Retry-After, never a 5xx, and every
  decision is journaled and mirrored into the flight ring under
  invariant 20's no-fail rule (sinks may drop, never raise).
- :class:`BrownoutController` — the same hysteresis/streak/cooldown
  decision shape as the autoscaler (``serve/autoscaler.py``), stepping
  through declared degradation levels under sustained SLO burn instead
  of replica counts: level 1 sheds the batch class, level 2 additionally
  serves warm-cache hits + tier-1 only (no cascade escalation), level 3
  sheds interactive as the last resort. Each transition is journaled as
  a ``brownout_transition`` event and ``/healthz`` reports the level
  honestly.

The interactive class sheds last (invariant candidate 30): batch gets
the smaller token budget, the depth guard binds batch only, and the
brownout ladder reaches interactive only at its final level.

Chaos points (``DEEPDFA_FAULTS``): ``admission.bucket_exhausted`` drains
one bucket at admission, ``admission.deadline_blown`` forces one
deadline check to judge the wait as blown, ``admission.brownout_force``
pushes the brownout controller one level deeper on its next poll — all
three must degrade to the declared 429/brownout behavior, never a 5xx.
"""

from __future__ import annotations

import logging
import math
import threading
import time
from collections import deque

from deepdfa_tpu.resilience import faults

__all__ = [
    "QOS_CLASSES",
    "BROWNOUT_LEVELS",
    "BROWNOUT_SHED_BATCH",
    "BROWNOUT_TIER1_ONLY",
    "BROWNOUT_SHED_INTERACTIVE",
    "TokenBucket",
    "AdmissionController",
    "BrownoutController",
]

logger = logging.getLogger(__name__)

# the two priority classes, in shed order LAST to FIRST: batch (rescore
# traffic) sheds first, interactive (a human waiting on a score) last
QOS_CLASSES = ("interactive", "batch")

# the declared brownout ladder; each level includes everything above it
BROWNOUT_SHED_BATCH = 1  # shed the batch class
BROWNOUT_TIER1_ONLY = 2  # + serve warm-cache hits + tier-1 only
BROWNOUT_SHED_INTERACTIVE = 3  # + shed interactive (last resort)
BROWNOUT_LEVELS = {
    0: "normal",
    BROWNOUT_SHED_BATCH: "shed_batch",
    BROWNOUT_TIER1_ONLY: "cache_tier1_only",
    BROWNOUT_SHED_INTERACTIVE: "shed_interactive",
}

# bounded decision memory on both controllers: sustained overload sheds
# thousands of requests and the server is long-lived, so raw decisions
# ride a ring while the summary() counters stay exact
DECISION_RING = 4096


class TokenBucket:
    """One refill bucket. All state transitions go through the injected
    clock, so tests drive time explicitly and Retry-After is exactly
    reproducible: it is the ceil of the token deficit over the refill
    rate — the earliest whole second at which a retry can succeed."""

    def __init__(self, rate: float, burst: float, clock=time.monotonic):
        if rate <= 0 or burst <= 0:
            raise ValueError("rate and burst must be > 0")
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self._tokens = float(burst)
        self._t_last = clock()
        self._lock = threading.Lock()

    def _refill_locked(self, now: float) -> None:
        self._tokens = min(
            self.burst, self._tokens + max(0.0, now - self._t_last) * self.rate)
        self._t_last = now

    def try_take(self, n: float = 1.0) -> bool:
        with self._lock:
            self._refill_locked(self._clock())
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def drain(self) -> None:
        """Empty the bucket (the ``admission.bucket_exhausted`` chaos
        point uses this so the fault exercises the REAL shed path)."""
        with self._lock:
            self._refill_locked(self._clock())
            self._tokens = 0.0

    def tokens(self) -> float:
        with self._lock:
            self._refill_locked(self._clock())
            return self._tokens

    def retry_after_s(self, n: float = 1.0) -> int:
        """Whole seconds until the bucket holds ``n`` tokens — pure
        function of (deficit, rate), floor 1 (RFC 7231 Retry-After is an
        integer and "retry immediately" is never the answer to a shed)."""
        with self._lock:
            self._refill_locked(self._clock())
            deficit = max(0.0, n - self._tokens)
        return max(1, math.ceil(deficit / self.rate))


class AdmissionController:
    """The per-request admit/shed decision, in signal-priority order:
    brownout class policy, then the (tenant, class) token bucket, then
    the deadline check against the observed frontend queue-wait p99 and
    the queue-depth guard. Decision dicts carry everything the bench
    gates on: class, tenant, reason, Retry-After, and the brownout level
    at decision time (the "only batch sheds before brownout escalates"
    gate reads that field)."""

    def __init__(self, cfg, metrics=None, journal=None, flight=None,
                 clock=time.monotonic):
        self.cfg = cfg
        self.metrics = metrics
        self.journal = journal
        self.flight = flight
        self._clock = clock
        self.brownout: BrownoutController | None = None
        self._lock = threading.Lock()
        self._buckets: dict[tuple[str, str], TokenBucket] = {}
        self._decisions: deque[dict] = deque(maxlen=DECISION_RING)
        self._admitted: dict[str, int] = {}
        self._shed: dict[str, int] = {}
        self._shed_reasons: dict[str, int] = {}
        # interactive sheds while the brownout ladder had NOT reached its
        # last level — the "interactive sheds last" gate counts these
        # exactly (the decision ring is bounded; this counter is not)
        self._early_interactive_sheds = 0
        self._journal_drops = 0
        self._t0 = clock()

    # -- buckets -------------------------------------------------------------

    def _bucket(self, tenant: str, klass: str) -> TokenBucket:
        with self._lock:
            bucket = self._buckets.get((tenant, klass))
            if bucket is None:
                cfg = self.cfg
                rate, burst = (
                    (cfg.interactive_rate, cfg.interactive_burst)
                    if klass == "interactive"
                    else (cfg.batch_rate, cfg.batch_burst))
                bucket = TokenBucket(rate, burst, clock=self._clock)
                self._buckets[(tenant, klass)] = bucket
            return bucket

    # -- the decision --------------------------------------------------------

    def level(self) -> int:
        return self.brownout.level if self.brownout is not None else 0

    def admit(self, tenant: str, klass: str) -> dict:
        """One request's verdict: ``{"admit": True, ...}`` or a shed dict
        with ``reason`` and a deterministic ``retry_after_s``."""
        level = self.level()
        bucket = self._bucket(tenant, klass)
        # brownout class policy first: a browned-out class sheds without
        # consuming a token (its budget stays intact for recovery)
        if klass == "batch" and level >= BROWNOUT_SHED_BATCH:
            return self._shed_decision(tenant, klass, "brownout", bucket, level)
        if klass == "interactive" and level >= BROWNOUT_SHED_INTERACTIVE:
            return self._shed_decision(tenant, klass, "brownout", bucket, level)
        if faults.fire("admission.bucket_exhausted"):
            bucket.drain()  # the fault drives the REAL exhaustion path
        if not bucket.try_take():
            return self._shed_decision(
                tenant, klass, "bucket_exhausted", bucket, level)
        if self._deadline_blown(klass):
            return self._shed_decision(
                tenant, klass, "deadline_blown", bucket, level)
        with self._lock:
            self._admitted[klass] = self._admitted.get(klass, 0) + 1
        if self.metrics is not None:
            self.metrics.observe_admission(klass, admitted=True)
        return {"admit": True, "class": klass, "tenant": tenant,
                "level": level}

    def _deadline_blown(self, klass: str) -> bool:
        """Deadline-aware shedding off the signals that already exist:
        the frontend queue-wait reservoir p99 (the admission layer,
        autoscaler and /healthz all read this one surface) and the
        queue-depth guard, which binds the batch class only — depth
        pressure is exactly when batch must yield to interactive."""
        if faults.fire("admission.deadline_blown"):
            return True
        cfg, m = self.cfg, self.metrics
        if m is None:
            return False
        deadline_ms = (cfg.interactive_deadline_ms if klass == "interactive"
                       else cfg.batch_deadline_ms)
        wait_p99 = m.frontend_queue_wait.quantile(0.99)
        if wait_p99 is not None and wait_p99 > deadline_ms:
            return True
        if klass == "batch" and cfg.depth_shed_factor > 0:
            if m.frontend_queue_depth > cfg.depth_shed_factor * cfg.batch_burst:
                return True
        return False

    def _shed_decision(self, tenant: str, klass: str, reason: str,
                       bucket: TokenBucket, level: int) -> dict:
        retry_after = bucket.retry_after_s()
        decision = {
            "admit": False, "class": klass, "tenant": tenant,
            "reason": reason, "retry_after_s": retry_after, "level": level,
            "t": round(self._clock() - self._t0, 3),
        }
        with self._lock:
            self._decisions.append(decision)
            self._shed[klass] = self._shed.get(klass, 0) + 1
            self._shed_reasons[reason] = self._shed_reasons.get(reason, 0) + 1
            if (klass == "interactive"
                    and level < BROWNOUT_SHED_INTERACTIVE):
                self._early_interactive_sheds += 1
        if self.metrics is not None:
            self.metrics.observe_admission(klass, admitted=False)
        if self.journal is not None:
            try:
                self.journal.write(event="admission_shed", **{
                    k: v for k, v in decision.items() if k != "admit"})
            except Exception:  # noqa: BLE001 — invariant 20: sinks never
                # fail the decision they record; drops are counted
                with self._lock:
                    self._journal_drops += 1
                logger.warning("admission journal write dropped")
        if self.flight is not None:
            self.flight.record("admission.shed", **{
                k: v for k, v in decision.items() if k != "admit"})
        return decision

    # -- observability -------------------------------------------------------

    def summary(self) -> dict:
        """The bench/artifact view: exact per-class counters plus the
        recent decision ring (bounded — counters, not the ring, are the
        totals)."""
        with self._lock:
            return {
                "admitted": dict(self._admitted),
                "shed": dict(self._shed),
                "shed_reasons": dict(self._shed_reasons),
                "shed_total": sum(self._shed.values()),
                "interactive_sheds_before_brownout":
                    self._early_interactive_sheds,
                "journal_drops": self._journal_drops,
                "decisions": [dict(d) for d in self._decisions],
            }


class BrownoutController:
    """The brownout decision loop: hysteresis watermarks over the worst
    fast-window SLO burn, consecutive-poll streaks, and a post-action
    cooldown — :meth:`poll_once` is shape-for-shape the autoscaler's
    ``_decide_scale``, stepping a degradation level instead of a replica
    count. ``burn_fn`` is the signal source (the server passes its own
    SLO engine's worst fast burn; tests inject a script)."""

    def __init__(self, cfg, burn_fn, metrics=None, journal=None, flight=None,
                 clock=time.monotonic):
        self._cfg = cfg
        self._burn_fn = burn_fn
        self._metrics = metrics
        self._journal = journal
        self._flight = flight
        self._clock = clock
        self._lock = threading.Lock()
        self._level = 0
        self._streak_up = 0
        self._streak_down = 0
        self._last_action_t: float | None = None
        self._transitions: deque[dict] = deque(maxlen=DECISION_RING)
        self._transitions_total = 0
        self._journal_drops = 0
        self._t0 = clock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    @property
    def level(self) -> int:
        with self._lock:
            return self._level

    @property
    def level_name(self) -> str:
        return BROWNOUT_LEVELS[self.level]

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "BrownoutController":
        self._thread = threading.Thread(target=self._run, name="brownout",
                                        daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self._cfg.poll_interval_s):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — the controller never dies
                logger.exception("brownout poll failed; continuing")

    def stop(self) -> dict:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        return self.summary()

    # -- one decision tick ---------------------------------------------------

    def poll_once(self) -> list[dict]:
        """One tick: chaos first (``admission.brownout_force`` pushes one
        level deeper regardless of burn — the honest-degradation paths
        must hold even when the signal lies), then the hysteresis
        decision over the observed burn."""
        if faults.fire("admission.brownout_force"):
            with self._lock:
                level = self._level
            if level >= self._cfg.max_level:
                return []
            return [self._transition(level, level + 1, burn=None,
                                     reason="fault_injected")]
        burn = self._burn_fn()
        if burn is None:
            return []
        now = self._clock()
        cfg = self._cfg
        with self._lock:
            # hysteresis: streaks advance only outside the dead band, and
            # any excursion into the opposite band resets the other side
            if burn >= cfg.burn_high:
                self._streak_up += 1
                self._streak_down = 0
            elif burn <= cfg.burn_low:
                self._streak_down += 1
                self._streak_up = 0
            else:
                self._streak_up = 0
                self._streak_down = 0
            up = self._streak_up >= cfg.up_consecutive
            down = self._streak_down >= cfg.down_consecutive
            cooling = (self._last_action_t is not None
                       and now - self._last_action_t < cfg.cooldown_s)
            level = self._level
        if cooling or not (up or down):
            return []
        if up:
            if level >= cfg.max_level:
                self._reset_streaks()
                return []
            return [self._transition(level, level + 1, burn=burn,
                                     reason="burn_high")]
        if level <= 0:
            self._reset_streaks()
            return []
        return [self._transition(level, level - 1, burn=burn,
                                 reason="burn_low")]

    def _reset_streaks(self, acted: bool = False) -> None:
        with self._lock:
            self._streak_up = 0
            self._streak_down = 0
            if acted:
                self._last_action_t = self._clock()

    def _transition(self, level_from: int, level_to: int,
                    burn: float | None, reason: str) -> dict:
        transition = {
            "level_from": level_from, "level_to": level_to,
            "level_name": BROWNOUT_LEVELS[level_to], "reason": reason,
            "burn": round(burn, 3) if burn is not None else None,
            "t": round(self._clock() - self._t0, 3),
        }
        with self._lock:
            self._level = level_to
            self._transitions.append(transition)
            self._transitions_total += 1
        self._reset_streaks(acted=True)
        if self._metrics is not None:
            self._metrics.set_gauge("brownout_level", level_to)
            self._metrics.inc("brownout_transitions_total")
        if self._journal is not None:
            try:
                self._journal.write(event="brownout_transition", **transition)
            except Exception:  # noqa: BLE001 — invariant 20
                with self._lock:
                    self._journal_drops += 1
                logger.warning("brownout journal write dropped")
        if self._flight is not None:
            self._flight.record("brownout.transition", **transition)
        logger.warning("brownout %s -> %s (%s)",
                       BROWNOUT_LEVELS[level_from], BROWNOUT_LEVELS[level_to],
                       reason)
        return transition

    # -- observability -------------------------------------------------------

    def summary(self) -> dict:
        with self._lock:
            return {
                "level": self._level,
                "level_name": BROWNOUT_LEVELS[self._level],
                "max_level_seen": max(
                    (t["level_to"] for t in self._transitions),
                    default=self._level),
                "transitions": [dict(t) for t in self._transitions],
                "transitions_total": self._transitions_total,
                "journal_drops": self._journal_drops,
            }
