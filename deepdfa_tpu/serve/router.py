"""Shared-nothing fleet router: consistent-hash ``source_key`` sharding.

One :class:`~deepdfa_tpu.serve.server.ScoreServer` owns one in-process
:class:`~deepdfa_tpu.serve.cache.ScanCache`. Run N of them behind a
round-robin LB and every replica re-scans (and re-caches) the same
sources — N× the memory for 1× the hit rate. The router fixes the
topology instead of the cache: requests are routed by the SAME content
address the cache keys on (``pipeline.source_key``, sha256 of the
whitespace-normalized source), so each source lands on exactly one
backend and the fleet's cache is the union of N disjoint shards.

Routing is a consistent-hash ring (``vnodes`` points per backend from
sha256, binary-searched): a backend joining or leaving remaps only
~1/N of the keyspace — the other shards keep their hits, which is the
entire point versus ``hash(key) % N``.

Backend lifecycle mirrors the PR 5 elasticity invariants:

- **readiness-gated registration** — a configured backend enters the
  ring only after a ``/healthz`` 200 whose body says the bucket ladder
  is warm; a replica that is still compiling takes no traffic;
- **health probes** — a background thread re-probes every backend on an
  interval; a connection failure or 5xx takes it out of the ring
  (state ``down``) until it probes healthy again;
- **drain-aware rebalancing** — a backend answering 503/``draining``
  (its SIGTERM flag) leaves the ring immediately; its keyspace slides
  to ring neighbours while in-flight requests finish. The router's own
  SIGTERM sets the same flag-only drain: ``/healthz`` goes 503, new
  scores get 503, in-flight forwards complete.

Per-request failover: a forward that fails at the socket marks the
backend down and retries the next ring node (bounded by the live
backend count) — one crashed replica costs its cache shard, not its
keyspace's availability.
"""

from __future__ import annotations

import bisect
import hashlib
import http.client
import json
import logging
import signal
import threading
import time
from contextlib import nullcontext
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from deepdfa_tpu.config import ObsConfig
from deepdfa_tpu.obs import (
    MetricsRegistry,
    SLOEngine,
    Tracer,
    parse_traceparent,
    router_specs,
)
from deepdfa_tpu.pipeline import source_key

from .metrics import LatencyReservoir

__all__ = ["HashRing", "Backend", "RouterMetrics", "FleetRouter", "main"]

logger = logging.getLogger(__name__)

DEFAULT_VNODES = 64
FORWARD_TIMEOUT_S = 90.0  # one backend round-trip (covers a cold compile)


def _ring_hash(token: str) -> int:
    return int.from_bytes(hashlib.sha256(token.encode()).digest()[:8], "big")


class HashRing:
    """Consistent-hash ring with virtual nodes. ``route(key)`` walks
    clockwise from the key's point to the first live node; ``exclude``
    keeps walking past named nodes (per-request failover)."""

    def __init__(self, vnodes: int = DEFAULT_VNODES):
        self.vnodes = int(vnodes)
        self._points: list[int] = []     # sorted ring positions
        self._owners: list[str] = []     # node name at each position
        self._nodes: set[str] = set()
        self._lock = threading.Lock()

    @property
    def nodes(self) -> set[str]:
        with self._lock:
            return set(self._nodes)

    def __len__(self) -> int:
        return len(self.nodes)

    def add(self, name: str) -> None:
        with self._lock:
            if name in self._nodes:
                return
            self._nodes.add(name)
            for i in range(self.vnodes):
                pt = _ring_hash(f"{name}#{i}")
                idx = bisect.bisect(self._points, pt)
                self._points.insert(idx, pt)
                self._owners.insert(idx, name)

    def remove(self, name: str) -> None:
        with self._lock:
            if name not in self._nodes:
                return
            self._nodes.discard(name)
            keep = [(p, o) for p, o in zip(self._points, self._owners)
                    if o != name]
            self._points = [p for p, _ in keep]
            self._owners = [o for _, o in keep]

    def route(self, key: str, exclude=frozenset()) -> str | None:
        """Owner of ``key``, skipping ``exclude``; None when no eligible
        node remains."""
        with self._lock:
            if not self._points:
                return None
            candidates = self._nodes - set(exclude)
            if not candidates:
                return None
            start = bisect.bisect(self._points, _ring_hash(key))
            n = len(self._points)
            for step in range(n):
                owner = self._owners[(start + step) % n]
                if owner in candidates:
                    return owner
            return None


@dataclass
class Backend:
    """One ScoreServer the router fronts. ``state`` transitions:
    pending → ready (first warm healthz 200) → draining/down → ready."""

    name: str                     # "host:port" — also the ring node name
    host: str
    port: int
    state: str = "pending"
    health: dict = field(default_factory=dict)  # last healthz body
    forwarded: int = 0
    failures: int = 0

    @classmethod
    def parse(cls, spec: str) -> "Backend":
        host, _, port = spec.rpartition(":")
        return cls(name=spec, host=host or "127.0.0.1", port=int(port))


class RouterMetrics:
    """Router-side counters; rendered as ``deepdfa_router_*``."""

    def __init__(self, latency_window: int = 2048):
        self._lock = threading.Lock()
        self.requests_total = 0
        self.forwarded_total: dict[str, int] = {}
        self.retries_total = 0
        self.no_backend_total = 0
        self.errors_total = 0
        self.latency = LatencyReservoir(latency_window)
        self.tracer = None  # attachment point set by the router

    def inc(self, name: str, by: int = 1) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + by)

    def observe_forward(self, backend: str) -> None:
        with self._lock:
            self.forwarded_total[backend] = (
                self.forwarded_total.get(backend, 0) + 1)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "requests_total": self.requests_total,
                "forwarded_total": dict(self.forwarded_total),
                "retries_total": self.retries_total,
                "no_backend_total": self.no_backend_total,
                "errors_total": self.errors_total,
                "latency_p50_ms": self.latency.quantile(0.50),
                "latency_p99_ms": self.latency.quantile(0.99),
            }

    def render(self) -> str:
        """Prometheus text via the shared registry (one ``# HELP`` +
        ``# TYPE`` per family, same renderer as serve + train)."""
        snap = self.snapshot()
        reg = MetricsRegistry("deepdfa_router_")
        reg.counter("requests_total", "Every /score the router received").set(
            snap["requests_total"])
        fwd = reg.counter("forwarded_total", "Forwards by backend",
                          labels=("backend",))
        for name, n in snap["forwarded_total"].items():
            fwd.set(n, backend=name)
        reg.counter("retries_total",
                    "Per-request failovers past a dead backend").set(
            snap["retries_total"])
        reg.counter("no_backend_total",
                    "Requests with no ready backend").set(
            snap["no_backend_total"])
        reg.counter("errors_total", "4xx/5xx responses").set(
            snap["errors_total"])
        lat = reg.gauge("latency_ms",
                        "Router round-trip latency (windowed quantiles)",
                        labels=("quantile",))
        for q in (0.50, 0.99):
            lat.set(self.latency.quantile(q), quantile=q)
        tracer = self.tracer
        if tracer is not None:
            reg.counter("trace_spans_total",
                        "Spans recorded by the router tracer").set(
                tracer.recorded_total)
            reg.counter("trace_spans_dropped_total",
                        "Spans lost at export (never fatal)").set(
                tracer.dropped_total)
        return reg.render()


class FleetRouter:
    """The fleet's one client-facing surface.

    ``POST /score`` computes the body's ``source_key``, routes it on the
    ring, and proxies the backend's response verbatim (plus an
    ``X-DeepDFA-Backend`` header naming the shard). ``GET /healthz``
    reports the router + per-backend states; ``GET /metrics`` the
    ``deepdfa_router_*`` counters."""

    def __init__(self, backends, host: str = "127.0.0.1", port: int = 0,
                 vnodes: int = DEFAULT_VNODES,
                 probe_interval_s: float = 2.0,
                 metrics: RouterMetrics | None = None,
                 obs: ObsConfig | None = None,
                 allow_empty: bool = False):
        # membership is dynamic (the autoscaler adds/removes ring members
        # over /admin/backends at runtime), so every read of the table
        # snapshots under this lock
        self._backends_lock = threading.Lock()
        self.backends: dict[str, Backend] = {}
        for spec in backends:
            b = spec if isinstance(spec, Backend) else Backend.parse(str(spec))
            self.backends[b.name] = b
        if not self.backends and not allow_empty:
            raise ValueError("router needs at least one backend")
        self.ring = HashRing(vnodes)
        self.metrics = metrics or RouterMetrics()
        obs = obs or ObsConfig()
        self.tracer = Tracer(
            proc="router", max_spans=obs.trace_buffer,
            slow_ms=(obs.slow_trace_ms
                     if obs.slow_trace_ms and obs.slow_trace_ms > 0
                     else None),
            exemplar_dir=obs.trace_dir, max_exemplars=obs.max_exemplars,
        ) if obs.trace else None
        self.metrics.tracer = self.tracer
        # the router's verdict layer: availability + p99 SLOs judged from
        # its own snapshot at /slo scrape time (invariant 16: same
        # registry renderer as every other endpoint)
        self.slo = SLOEngine(
            router_specs(availability=obs.slo_availability,
                         p99_ms=obs.slo_p99_ms),
            fast_window_s=obs.slo_fast_window_s,
            slow_window_s=obs.slo_slow_window_s,
            burn_threshold=obs.slo_burn_threshold)
        self.probe_interval_s = float(probe_interval_s)
        self._draining = threading.Event()
        self._stop_requested = threading.Event()
        self._probe_thread: threading.Thread | None = None
        self.httpd = ThreadingHTTPServer((host, port), _make_handler(self))
        self.httpd.daemon_threads = True
        self._serve_thread: threading.Thread | None = None

    # -- lifecycle ----------------------------------------------------------

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    @property
    def draining(self) -> bool:
        return self._draining.is_set() or self._stop_requested.is_set()

    def start(self, probe: bool = True) -> "FleetRouter":
        if probe:
            self.probe_once()
            self._probe_thread = threading.Thread(
                target=self._probe_loop, name="router-probe", daemon=True)
            self._probe_thread.start()
        self._serve_thread = threading.Thread(
            target=self.httpd.serve_forever, name="router-http", daemon=True)
        self._serve_thread.start()
        logger.info("routing on :%s over %d backend(s), %d ready",
                    self.port, len(self._backend_list()), len(self.ring))
        return self

    def install_signal_handlers(self) -> None:
        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, lambda *_: self._stop_requested.set())

    def wait(self) -> dict:
        while not self._stop_requested.wait(timeout=0.2):
            pass
        return self.shutdown()

    def request_stop(self) -> None:
        self._stop_requested.set()

    def request_drain(self) -> None:
        """Flag-only cell-level drain (invariant 6 one level up): new
        ``/score``s get 503, ``/healthz`` goes 503/``draining`` so the
        federation drops this cell from its ring, in-flight forwards
        finish. The process keeps serving — ``clear_drain`` reverses it."""
        self._draining.set()

    def clear_drain(self) -> None:
        """Reverse a flag-only drain: the next federation probe finds the
        cell healthy again and readmits it (readiness-gated, invariant
        13). A SIGTERM-initiated stop is NOT reversible."""
        self._draining.clear()

    def shutdown(self) -> dict:
        self._draining.set()
        self._stop_requested.set()
        self.httpd.shutdown()
        self.httpd.server_close()
        return self.metrics.snapshot()

    def render_slo(self) -> str:
        """The ``/slo`` body: the router's snapshot is already flat
        (errors_total / requests_total / latency_p99_ms), so it feeds
        the engine directly. Never fails the scrape (invariant 14)."""
        self.slo.observe(self.metrics.snapshot())
        return self.slo.render("deepdfa_router_")

    # -- dynamic membership (the autoscaler's actuation surface) ------------

    def add_backend(self, spec) -> Backend:
        """Register a backend at runtime. It enters as ``pending`` and
        joins the ring only after the next probe finds it warm — the same
        readiness gate as construction-time members (invariant 13), so the
        autoscaler can never admit a cold replica by registering early."""
        b = spec if isinstance(spec, Backend) else Backend.parse(str(spec))
        with self._backends_lock:
            existing = self.backends.get(b.name)
            if existing is not None:
                return existing
            self.backends[b.name] = b
        self._probe_backend(b)
        logger.info("backend %s registered (state %s)", b.name, b.state)
        return b

    def remove_backend(self, name: str) -> bool:
        """Deregister a backend: out of the ring immediately (its keyspace
        slides to ring neighbours), out of the table. The caller owns the
        replica's drain — the router never signals processes."""
        with self._backends_lock:
            b = self.backends.pop(name, None)
        if b is None:
            return False
        self.ring.remove(name)
        logger.info("backend %s deregistered", name)
        return True

    def _backend_list(self) -> list[Backend]:
        with self._backends_lock:
            return list(self.backends.values())

    def _get_backend(self, name: str) -> Backend | None:
        with self._backends_lock:
            return self.backends.get(name)

    # -- backend health -----------------------------------------------------

    def _probe_backend(self, b: Backend) -> None:
        try:
            conn = http.client.HTTPConnection(b.host, b.port, timeout=5.0)
            try:
                conn.request("GET", "/healthz")
                resp = conn.getresponse()
                body = json.loads(resp.read() or b"{}")
            finally:
                conn.close()
        except (OSError, json.JSONDecodeError) as exc:
            self._mark(b, "down", {"error": f"{type(exc).__name__}: {exc}"})
            return
        if resp.status == 200 and not body.get("draining"):
            # readiness gate: only a WARM replica joins the ring — a
            # compiling one would stall its whole keyspace
            if body.get("warm", True):
                self._mark(b, "ready", body)
            else:
                self._mark(b, "pending", body)
        elif body.get("draining"):
            self._mark(b, "draining", body)
        else:
            self._mark(b, "down", body)

    def _mark(self, b: Backend, state: str, health: dict) -> None:
        prev = b.state
        b.state = state
        b.health = health
        if state == "ready":
            self.ring.add(b.name)
        else:
            self.ring.remove(b.name)
        if state != prev:
            logger.info("backend %s: %s -> %s", b.name, prev, state)

    def probe_once(self) -> dict:
        """Probe every backend once; returns ``{name: state}``."""
        snapshot = self._backend_list()
        for b in snapshot:
            self._probe_backend(b)
        return {b.name: b.state for b in snapshot}

    def _probe_loop(self) -> None:
        while not self._stop_requested.wait(timeout=self.probe_interval_s):
            self.probe_once()

    # -- request path -------------------------------------------------------

    def _span(self, name: str, parent=None, root: bool = False, **attrs):
        if self.tracer is None:
            return nullcontext()
        return self.tracer.span(name, parent=parent, root=root, **attrs)

    def handle_score(self, raw: bytes) -> tuple[int, dict, dict]:
        """Route + forward one ``/score`` body. Returns
        ``(status, body, extra_headers)``."""
        if self.draining:
            return 503, {"error": "router is draining"}, {}
        try:
            payload = json.loads(raw or b"{}")
        except json.JSONDecodeError:
            return 400, {"error": "body is not valid JSON"}, {}
        source = payload.get("source") if isinstance(payload, dict) else None
        if not isinstance(source, str) or not source.strip():
            return 400, {"error": "body must be JSON with a 'source' string"}, {}
        with self._span("router.route") as sp:
            key = source_key(source)
            if sp is not None:
                sp.attrs["key"] = key[:16]

        tried: set[str] = set()
        max_hops = max(1, len(self.ring))
        for _ in range(max_hops):
            name = self.ring.route(key, exclude=tried)
            if name is None:
                break
            b = self._get_backend(name)
            if b is None:  # deregistered between route and lookup
                self.ring.remove(name)
                tried.add(name)
                continue
            try:
                # the forward span's context rides the hop as the
                # traceparent header: the backend's server.request span
                # parents itself under it, one trace across both procs
                with self._span("router.forward", backend=name) as sp:
                    status, body = self._forward(
                        b, raw, ctx=None if sp is None else sp.ctx)
                    if sp is not None:
                        sp.attrs["code"] = status
            except OSError as exc:
                tried.add(name)
                b.failures += 1
                self._mark(b, "down",
                           {"error": f"{type(exc).__name__}: {exc}"})
                self.metrics.inc("retries_total")
                logger.warning("forward to %s failed (%s) — failing over",
                               name, type(exc).__name__)
                continue
            if status == 503 and "draining" in str(
                    (body or {}).get("error", "")):
                # stale ring: the backend started draining between route
                # and forward. Scoring is idempotent, so the request
                # fails over; only the probe-confirmed drain is terminal.
                tried.add(name)
                self._mark(b, "draining", {"error": body.get("error")})
                self.metrics.inc("retries_total")
                logger.info("backend %s draining — failing over", name)
                continue
            b.forwarded += 1
            self.metrics.observe_forward(name)
            extra = {"X-DeepDFA-Backend": name}
            if status == 429 and isinstance(body, dict) \
                    and body.get("retry_after_s") is not None:
                # a shed's deterministic Retry-After survives the proxy —
                # the federation (and any client) reads the header, not
                # the body (invariant 30)
                extra["Retry-After"] = str(int(body["retry_after_s"]))
            return status, body, extra
        self.metrics.inc("no_backend_total")
        return 503, {"error": "no ready backend for this key"}, {}

    def _forward(self, b: Backend, raw: bytes,
                 ctx=None) -> tuple[int, dict]:
        headers = {"Content-Type": "application/json"}
        if ctx is not None:
            headers["traceparent"] = ctx.traceparent()
        conn = http.client.HTTPConnection(b.host, b.port,
                                          timeout=FORWARD_TIMEOUT_S)
        try:
            conn.request("POST", "/score", body=raw, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
        finally:
            conn.close()
        try:
            return resp.status, json.loads(data or b"{}")
        except json.JSONDecodeError:
            return 502, {"error": "backend returned invalid JSON"}

    def admin_backends(self) -> tuple[int, dict]:
        """``GET /admin/backends``: the membership table as the autoscaler
        sees it (states, ring membership, forward/failure counters)."""
        return 200, {
            "ready": sorted(self.ring.nodes),
            "backends": {b.name: {"state": b.state,
                                  "replica_id": b.health.get("replica_id"),
                                  "forwarded": b.forwarded,
                                  "failures": b.failures}
                         for b in self._backend_list()},
        }

    def handle_admin(self, raw: bytes) -> tuple[int, dict]:
        """``POST /admin/backends``: ``{"action": "add"|"remove",
        "backend": "host:port"}`` — the runtime membership surface the
        autoscaler drives. Add is readiness-gated (the member enters
        ``pending`` and must probe warm before taking traffic); remove
        only drops ring membership — draining the process stays with the
        caller, so the router can never hard-kill a replica."""
        try:
            payload = json.loads(raw or b"{}")
        except json.JSONDecodeError:
            return 400, {"error": "body is not valid JSON"}
        action = payload.get("action") if isinstance(payload, dict) else None
        spec = payload.get("backend") if isinstance(payload, dict) else None
        if action not in ("add", "remove") or not isinstance(spec, str) \
                or ":" not in spec:
            return 400, {"error": "need {'action': 'add'|'remove', "
                                  "'backend': 'host:port'}"}
        if action == "add":
            b = self.add_backend(spec)
            return 200, {"backend": b.name, "state": b.state}
        removed = self.remove_backend(spec)
        return (200 if removed else 404), {"backend": spec,
                                           "removed": removed}

    def handle_admin_drain(self, raw: bytes) -> tuple[int, dict]:
        """``POST /admin/drain``: ``{"action": "drain"|"undrain"}`` — the
        federation's cell-level deploy surface. Drain is flag-only: this
        router's ``/healthz`` goes 503/``draining`` (so the federation's
        next probe drops the cell from its ring), new ``/score``s get
        503, in-flight forwards finish. Undrain clears the flag; the cell
        rejoins through the same readiness gate as a new member."""
        try:
            payload = json.loads(raw or b"{}")
        except json.JSONDecodeError:
            return 400, {"error": "body is not valid JSON"}
        action = payload.get("action") if isinstance(payload, dict) else None
        if action not in ("drain", "undrain"):
            return 400, {"error": "need {'action': 'drain'|'undrain'}"}
        if action == "drain":
            self.request_drain()
        else:
            self.clear_drain()
        return 200, {"action": action, "draining": self.draining}

    def healthz(self) -> tuple[int, dict]:
        ready = sorted(self.ring.nodes)
        # the cell tells the truth one level up: the worst backend's
        # brownout level and queue-wait p99 ARE the cell's saturation
        # signal — the federation spills on these, no new probes
        brownout = 0
        queue_wait = 0.0
        for b in self._backend_list():
            if b.state != "ready":
                continue
            brownout = max(brownout, int(b.health.get("brownout_level") or 0))
            queue_wait = max(
                queue_wait,
                float(b.health.get("frontend_queue_wait_p99_ms") or 0.0))
        body = {
            "status": "draining" if self.draining else (
                "ok" if ready else "no_ready_backends"),
            "draining": self.draining,
            "warm": bool(ready),
            "brownout_level": brownout,
            "frontend_queue_wait_p99_ms": queue_wait,
            "ready_backends": ready,
            "backends": {b.name: {"state": b.state,
                                  "replica_id": b.health.get("replica_id"),
                                  "forwarded": b.forwarded,
                                  "failures": b.failures}
                         for b in self._backend_list()},
        }
        ok = bool(ready) and not self.draining
        return (200 if ok else 503), body


def _make_handler(router: FleetRouter):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):
            logger.debug("router http: " + fmt, *args)

        def _send(self, code: int, body, headers=None,
                  content_type="application/json"):
            data = (body.encode() if isinstance(body, str)
                    else json.dumps(body).encode())
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            if self.path == "/healthz":
                code, body = router.healthz()
                self._send(code, body)
            elif self.path == "/metrics":
                self._send(200, router.metrics.render(),
                           content_type="text/plain; version=0.0.4")
            elif self.path == "/slo":
                self._send(200, router.render_slo(),
                           content_type="text/plain; version=0.0.4")
            elif self.path == "/admin/backends":
                code, body = router.admin_backends()
                self._send(code, body)
            else:
                self._send(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            if self.path in ("/admin/backends", "/admin/drain"):
                handler = (router.handle_admin
                           if self.path == "/admin/backends"
                           else router.handle_admin_drain)
                try:
                    length = int(self.headers.get("Content-Length") or 0)
                    code, body = handler(self.rfile.read(length))
                except Exception as exc:  # noqa: BLE001
                    code, body = 500, {
                        "error": f"{type(exc).__name__}: {exc}"}
                self._send(code, body)
                return
            if self.path != "/score":
                self._send(404, {"error": f"no route {self.path}"})
                return
            t0 = time.perf_counter()
            router.metrics.inc("requests_total")
            try:
                length = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(length)
                parent = (parse_traceparent(self.headers.get("traceparent"))
                          if router.tracer is not None else None)
                with router._span("router.request", parent=parent,
                                  root=True) as sp:
                    code, body, extra = router.handle_score(raw)
                    if sp is not None:
                        sp.attrs["code"] = code
            except Exception as exc:  # noqa: BLE001 — request dies, router not
                code, body, extra = 500, {
                    "error": f"{type(exc).__name__}: {exc}"}, {}
            if code >= 400:
                router.metrics.inc("errors_total")
            self._send(code, body, headers=extra)
            router.metrics.latency.observe(
                (time.perf_counter() - t0) * 1000.0)

    return Handler


def main(argv=None) -> dict:
    import argparse

    parser = argparse.ArgumentParser(prog="deepdfa-tpu-route")
    parser.add_argument("--backend", action="append", default=[],
                        required=False, dest="backends", metavar="HOST:PORT",
                        help="a ScoreServer to front (repeatable)")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8900)
    parser.add_argument("--vnodes", type=int, default=DEFAULT_VNODES)
    parser.add_argument("--probe-interval", type=float, default=2.0,
                        dest="probe_interval_s")
    args = parser.parse_args(argv)
    if not args.backends:
        parser.error("need at least one --backend HOST:PORT")

    logging.basicConfig(level=logging.INFO)
    router = FleetRouter(args.backends, host=args.host, port=args.port,
                         vnodes=args.vnodes,
                         probe_interval_s=args.probe_interval_s)
    router.install_signal_handlers()
    router.start()
    print(json.dumps({"status": "routing", "port": router.port,
                      "backends": router.probe_once()}), flush=True)
    summary = router.wait()
    print(json.dumps({"status": "drained", **summary}), flush=True)
    return summary


if __name__ == "__main__":
    main()
