"""Serving metrics: thread-safe counters/gauges + a latency reservoir,
rendered in the Prometheus text exposition format at ``/metrics``.

Stdlib-only on purpose (the container has no prometheus_client, and the
serve path must not grow dependencies): counters are plain ints under one
lock, latency quantiles come from a bounded ring buffer — O(window) per
scrape, O(1) per request, and immune to unbounded growth on long-lived
servers.
"""

from __future__ import annotations

import threading
from collections import deque

__all__ = ["LatencyReservoir", "ServeMetrics"]


class LatencyReservoir:
    """Last-N latency samples (ms); p50/p99 over the window. A sliding
    window — not a lifetime histogram — so quantiles track CURRENT service
    health, which is what an operator paging on p99 wants."""

    def __init__(self, window: int = 2048):
        self._samples: deque[float] = deque(maxlen=max(1, int(window)))
        self._lock = threading.Lock()

    def observe(self, ms: float) -> None:
        with self._lock:
            self._samples.append(float(ms))

    def __len__(self) -> int:
        with self._lock:
            return len(self._samples)

    def quantile(self, q: float) -> float | None:
        """Nearest-rank quantile over the window; None when empty."""
        with self._lock:
            data = sorted(self._samples)
        if not data:
            return None
        idx = min(len(data) - 1, max(0, round(q * (len(data) - 1))))
        return data[idx]


class ServeMetrics:
    """The server's one metrics registry. Counter semantics:

    - ``requests_total`` — every ``/score`` request received;
    - ``responses_total[code]`` — responses by HTTP status;
    - ``dropped_total`` — requests rejected by admission control or the
      ``serve.drop_request`` fault point;
    - ``errors_total`` — 4xx/5xx responses (a subset view of responses);
    - ``batches_total`` / ``batch_graphs_total`` / ``occupancy_sum`` —
      dispatched micro-batches, real graphs in them, and the per-batch
      occupancy sum (real graphs ÷ bucket graph capacity), so
      ``occupancy_sum / batches_total`` is the mean batch occupancy;
    - ``queue_depth`` — gauge, requests waiting in the micro-batch queue;
    - ``inflight`` — gauge, ``/score`` requests currently being handled.

    Cache hit/miss counters live on the cache itself (:mod:`.cache`) and
    are merged into the rendering by the server.
    """

    def __init__(self, latency_window: int = 2048):
        self._lock = threading.Lock()
        self.requests_total = 0
        self.responses_total: dict[int, int] = {}
        self.errors_total = 0
        self.dropped_total = 0
        self.batches_total = 0
        self.batch_graphs_total = 0
        self.occupancy_sum = 0.0
        self.queue_depth = 0
        self.inflight = 0
        self.latency = LatencyReservoir(latency_window)
        self.warmup: dict | None = None  # last engine warmup report

    def set_warmup(self, report: dict) -> None:
        """Publish an engine warmup report (per-bucket compile seconds +
        warm-store hit/miss/saved counters) for /metrics scrapes."""
        with self._lock:
            self.warmup = dict(report)

    def inc(self, name: str, by: float = 1) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + by)

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            setattr(self, name, value)

    def observe_response(self, code: int, latency_ms: float) -> None:
        with self._lock:
            self.responses_total[code] = self.responses_total.get(code, 0) + 1
            if code >= 400:
                self.errors_total += 1
        self.latency.observe(latency_ms)

    def observe_batch(self, n_real: int, capacity: int) -> None:
        with self._lock:
            self.batches_total += 1
            self.batch_graphs_total += n_real
            self.occupancy_sum += n_real / max(capacity, 1)

    def mean_batch_occupancy(self) -> float | None:
        with self._lock:
            if not self.batches_total:
                return None
            return self.occupancy_sum / self.batches_total

    def snapshot(self) -> dict:
        """Point-in-time copy for JSON consumers (the bench, tests)."""
        with self._lock:
            snap = {
                "requests_total": self.requests_total,
                "responses_total": dict(self.responses_total),
                "errors_total": self.errors_total,
                "dropped_total": self.dropped_total,
                "batches_total": self.batches_total,
                "batch_graphs_total": self.batch_graphs_total,
                "occupancy_sum": self.occupancy_sum,
                "queue_depth": self.queue_depth,
                "inflight": self.inflight,
                "warmup": dict(self.warmup) if self.warmup else None,
            }
        snap["mean_batch_occupancy"] = (
            snap["occupancy_sum"] / snap["batches_total"]
            if snap["batches_total"] else None)
        snap["latency_p50_ms"] = self.latency.quantile(0.50)
        snap["latency_p99_ms"] = self.latency.quantile(0.99)
        return snap

    def render(self, cache_stats: dict | None = None) -> str:
        """Prometheus text format (`# TYPE` lines + samples)."""
        snap = self.snapshot()
        lines = []

        def emit(name, kind, value, labels=""):
            if value is None:
                return
            lines.append(f"# TYPE deepdfa_serve_{name} {kind}")
            lines.append(f"deepdfa_serve_{name}{labels} {value}")

        emit("requests_total", "counter", snap["requests_total"])
        for code in sorted(snap["responses_total"]):
            lines.append("# TYPE deepdfa_serve_responses_total counter")
            lines.append(
                f'deepdfa_serve_responses_total{{code="{code}"}} '
                f'{snap["responses_total"][code]}')
        emit("errors_total", "counter", snap["errors_total"])
        emit("dropped_total", "counter", snap["dropped_total"])
        emit("batches_total", "counter", snap["batches_total"])
        emit("batch_graphs_total", "counter", snap["batch_graphs_total"])
        emit("batch_occupancy_mean", "gauge", snap["mean_batch_occupancy"])
        emit("queue_depth", "gauge", snap["queue_depth"])
        emit("inflight", "gauge", snap["inflight"])
        for q in (0.50, 0.99):
            v = self.latency.quantile(q)
            if v is not None:
                lines.append("# TYPE deepdfa_serve_latency_ms gauge")
                lines.append(
                    f'deepdfa_serve_latency_ms{{quantile="{q}"}} {v}')
        warm = snap.get("warmup")
        if warm:
            emit("warm_store_hits_total", "counter", warm.get("hits"))
            emit("warm_store_misses_total", "counter", warm.get("misses"))
            emit("warm_store_compile_seconds_saved", "gauge",
                 warm.get("compile_seconds_saved"))
            for bucket, row in sorted((warm.get("per_bucket") or {}).items()):
                secs = row.get("compile_seconds")
                if secs is None:
                    continue
                lines.append(
                    "# TYPE deepdfa_serve_warmup_compile_seconds gauge")
                lines.append(
                    f'deepdfa_serve_warmup_compile_seconds'
                    f'{{bucket="{bucket}",source="{row.get("source")}"}} '
                    f'{secs}')
        if cache_stats:
            emit("cache_hits_total", "counter", cache_stats.get("hits"))
            emit("cache_encode_hits_total", "counter",
                 cache_stats.get("encode_hits"))
            emit("cache_misses_total", "counter", cache_stats.get("misses"))
            emit("cache_evictions_total", "counter",
                 cache_stats.get("evictions"))
            emit("cache_entries", "gauge", cache_stats.get("entries"))
            emit("cache_hit_rate", "gauge", cache_stats.get("hit_rate"))
        return "\n".join(lines) + "\n"
