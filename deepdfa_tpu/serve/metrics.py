"""Serving metrics: thread-safe counters/gauges + a latency reservoir,
rendered in the Prometheus text exposition format at ``/metrics``.

Stdlib-only on purpose (the container has no prometheus_client, and the
serve path must not grow dependencies): counters are plain ints under one
lock, latency quantiles come from a bounded ring buffer — O(window) per
scrape, O(1) per request, and immune to unbounded growth on long-lived
servers.
"""

from __future__ import annotations

import threading
from collections import deque

from deepdfa_tpu.obs.registry import MetricsRegistry

__all__ = ["LatencyReservoir", "ServeMetrics"]


class LatencyReservoir:
    """Last-N latency samples (ms); p50/p99 over the window. A sliding
    window — not a lifetime histogram — so quantiles track CURRENT service
    health, which is what an operator paging on p99 wants."""

    def __init__(self, window: int = 2048):
        self._samples: deque[float] = deque(maxlen=max(1, int(window)))
        self._lock = threading.Lock()

    def observe(self, ms: float) -> None:
        with self._lock:
            self._samples.append(float(ms))

    def __len__(self) -> int:
        with self._lock:
            return len(self._samples)

    def quantile(self, q: float) -> float | None:
        """Nearest-rank quantile over the window; None when empty."""
        with self._lock:
            data = sorted(self._samples)
        if not data:
            return None
        idx = min(len(data) - 1, max(0, round(q * (len(data) - 1))))
        return data[idx]


class ServeMetrics:
    """The server's one metrics registry. Counter semantics:

    - ``requests_total`` — every ``/score`` request received;
    - ``responses_total[code]`` — responses by HTTP status;
    - ``dropped_total`` — requests rejected by admission control or the
      ``serve.drop_request`` fault point;
    - ``errors_total`` — 4xx/5xx responses (a subset view of responses);
    - ``batches_total`` / ``batch_graphs_total`` / ``occupancy_sum`` —
      dispatched micro-batches, real graphs in them, and the per-batch
      occupancy sum (real graphs ÷ bucket graph capacity), so
      ``occupancy_sum / batches_total`` is the mean batch occupancy;
    - ``queue_depth`` — gauge, requests waiting in the micro-batch queue;
    - ``inflight`` — gauge, ``/score`` requests currently being handled;
    - ``padding_efficiency[bucket, axis]`` — gauge, the cumulative real ÷
      padded fraction per serving bucket and axis (nodes/edges/graphs):
      the fraction of each dispatched shape's budget occupied by real
      entries, i.e. the direct multiplier on useful FLOPs per dispatch.

    Cache hit/miss counters live on the cache itself (:mod:`.cache`) and
    are merged into the rendering by the server.
    """

    def __init__(self, latency_window: int = 2048):
        self._lock = threading.Lock()
        self.requests_total = 0
        self.responses_total: dict[int, int] = {}
        self.errors_total = 0
        self.dropped_total = 0
        self.batches_total = 0
        self.batch_graphs_total = 0
        self.occupancy_sum = 0.0
        self.queue_depth = 0
        self.inflight = 0
        # per-bucket padding accumulators: {bucket: {axis: [real, padded]}}
        # — cumulative, so the exported gauge is the lifetime efficiency
        # (stable under scrape timing, unlike a last-batch snapshot)
        self.padding: dict[str, dict[str, list[float]]] = {}
        self.latency = LatencyReservoir(latency_window)
        # stage-level reservoirs fed by the tracing instrumentation: time a
        # graph sat in the micro-batch queue, and time one engine dispatch
        # took — the split that locates a slow /score (bench_serving
        # records both in its notes block)
        self.queue_wait = LatencyReservoir(latency_window)
        self.dispatch = LatencyReservoir(latency_window)
        # cascade (serve/cascade.py): escalation counters + per-tier latency
        # reservoirs. answered counts key on the tier that produced the
        # served score; degraded = tier-2 failures converted to tier-1
        # answers (invariant 24 — they are NOT errors)
        self.cascade_escalated_total = 0
        self.cascade_degraded_total = 0
        self.cascade_answered: dict[int, int] = {}
        self.tier2_queue_depth = 0
        self.tier1_latency = LatencyReservoir(latency_window)
        self.tier2_latency = LatencyReservoir(latency_window)
        self.tier2_queue_wait = LatencyReservoir(latency_window)
        self.tier2_dispatch = LatencyReservoir(latency_window)
        # frontend encode pool (serve/frontend.py): queue-depth gauge,
        # degraded-to-inline counter (pool unavailable → inline encode,
        # invariant 25 — NOT an error), and the encode / queue-wait
        # reservoirs behind the /metrics p50-p99 gauges
        self.frontend_queue_depth = 0
        self.frontend_inline_total = 0
        self.frontend_encode = LatencyReservoir(latency_window)
        self.frontend_queue_wait = LatencyReservoir(latency_window)
        # admission control + brownout (serve/admission.py): per-class
        # admitted/shed counters (a shed is a 429 with a deterministic
        # Retry-After — invariant candidate 30, NOT an error), the current
        # brownout degradation level, its lifetime transition count, and
        # the cascade escalations suppressed at brownout level >= 2
        self.admission_admitted: dict[str, int] = {}
        self.admission_shed: dict[str, int] = {}
        self.brownout_level = 0
        self.brownout_transitions_total = 0
        self.brownout_suppressed_escalations_total = 0
        # wall-clock (start, end) of recent engine dispatches — the bench
        # intersects these with the frontend pool's encode intervals to
        # measure the encode↔dispatch overlap fraction
        self.dispatch_intervals: deque = deque(maxlen=4096)
        self.warmup: dict | None = None  # last engine warmup report
        # attachment points set by the server: the request tracer and the
        # score-drift sentinel both render through /metrics when present;
        # the flight recorder gets every assembled batch's shape
        self.tracer = None
        self.drift = None
        self.flight = None

    def set_warmup(self, report: dict) -> None:
        """Publish an engine warmup report (per-bucket compile seconds +
        warm-store hit/miss/saved counters) for /metrics scrapes."""
        with self._lock:
            self.warmup = dict(report)

    def inc(self, name: str, by: float = 1) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + by)

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            setattr(self, name, value)

    def observe_response(self, code: int, latency_ms: float) -> None:
        with self._lock:
            self.responses_total[code] = self.responses_total.get(code, 0) + 1
            if code >= 400:
                self.errors_total += 1
        self.latency.observe(latency_ms)

    def record_dispatch_interval(self, t0: float, t1: float) -> None:
        """One engine dispatch's wall-clock span (fed by the batcher)."""
        with self._lock:
            self.dispatch_intervals.append((float(t0), float(t1)))

    def dispatch_interval_list(self) -> list[tuple[float, float]]:
        with self._lock:
            return list(self.dispatch_intervals)

    def observe_answered(self, tier: int) -> None:
        """One served /score row attributed to the tier that scored it."""
        with self._lock:
            self.cascade_answered[tier] = self.cascade_answered.get(tier, 0) + 1

    def observe_admission(self, klass: str, admitted: bool) -> None:
        """One admission decision for priority class ``klass``."""
        with self._lock:
            table = self.admission_admitted if admitted else self.admission_shed
            table[klass] = table.get(klass, 0) + 1

    def observe_batch(self, n_real: int, capacity: int) -> None:
        with self._lock:
            self.batches_total += 1
            self.batch_graphs_total += n_real
            self.occupancy_sum += n_real / max(capacity, 1)
        if self.flight is not None:  # record() never raises (invariant 14)
            self.flight.record("batch", n_real=n_real, capacity=capacity)

    def observe_padding(self, bucket, real: dict, padded: dict) -> None:
        """Accumulate one dispatched batch's real vs padded counts per
        axis (``nodes``/``edges``/``graphs``) under the bucket's label."""
        with self._lock:
            acc = self.padding.setdefault(
                str(bucket), {ax: [0.0, 0.0] for ax in real})
            for ax, n in real.items():
                acc[ax][0] += float(n)
                acc[ax][1] += float(padded[ax])

    def padding_efficiency(self) -> dict[str, dict[str, float]]:
        """Cumulative real ÷ padded per bucket per axis."""
        with self._lock:
            return {bucket: {ax: (r / p if p else 0.0)
                             for ax, (r, p) in acc.items()}
                    for bucket, acc in self.padding.items()}

    def mean_batch_occupancy(self) -> float | None:
        with self._lock:
            if not self.batches_total:
                return None
            return self.occupancy_sum / self.batches_total

    def snapshot(self) -> dict:
        """Point-in-time copy for JSON consumers (the bench, tests)."""
        with self._lock:
            snap = {
                "requests_total": self.requests_total,
                "responses_total": dict(self.responses_total),
                "errors_total": self.errors_total,
                "dropped_total": self.dropped_total,
                "batches_total": self.batches_total,
                "batch_graphs_total": self.batch_graphs_total,
                "occupancy_sum": self.occupancy_sum,
                "queue_depth": self.queue_depth,
                "inflight": self.inflight,
                "warmup": dict(self.warmup) if self.warmup else None,
                "cascade_escalated_total": self.cascade_escalated_total,
                "cascade_degraded_total": self.cascade_degraded_total,
                "cascade_answered": dict(self.cascade_answered),
                "tier2_queue_depth": self.tier2_queue_depth,
                "frontend_queue_depth": self.frontend_queue_depth,
                "frontend_inline_total": self.frontend_inline_total,
                "admission_admitted": dict(self.admission_admitted),
                "admission_shed": dict(self.admission_shed),
                "brownout_level": self.brownout_level,
                "brownout_transitions_total": self.brownout_transitions_total,
                "brownout_suppressed_escalations_total":
                    self.brownout_suppressed_escalations_total,
            }
        snap["padding_efficiency"] = self.padding_efficiency()
        snap["mean_batch_occupancy"] = (
            snap["occupancy_sum"] / snap["batches_total"]
            if snap["batches_total"] else None)
        snap["latency_p50_ms"] = self.latency.quantile(0.50)
        snap["latency_p99_ms"] = self.latency.quantile(0.99)
        snap["queue_wait_p50_ms"] = self.queue_wait.quantile(0.50)
        snap["queue_wait_p99_ms"] = self.queue_wait.quantile(0.99)
        snap["dispatch_p50_ms"] = self.dispatch.quantile(0.50)
        snap["dispatch_p99_ms"] = self.dispatch.quantile(0.99)
        snap["tier1_latency_p50_ms"] = self.tier1_latency.quantile(0.50)
        snap["tier1_latency_p99_ms"] = self.tier1_latency.quantile(0.99)
        snap["tier2_latency_p50_ms"] = self.tier2_latency.quantile(0.50)
        snap["tier2_latency_p99_ms"] = self.tier2_latency.quantile(0.99)
        snap["tier2_queue_wait_p99_ms"] = self.tier2_queue_wait.quantile(0.99)
        snap["tier2_dispatch_p99_ms"] = self.tier2_dispatch.quantile(0.99)
        snap["frontend_encode_p50_ms"] = self.frontend_encode.quantile(0.50)
        snap["frontend_encode_p99_ms"] = self.frontend_encode.quantile(0.99)
        snap["frontend_queue_wait_p50_ms"] = (
            self.frontend_queue_wait.quantile(0.50))
        snap["frontend_queue_wait_p99_ms"] = (
            self.frontend_queue_wait.quantile(0.99))
        return snap

    def render(self, cache_stats: dict | None = None) -> str:
        """Prometheus text format via the shared registry: one ``# HELP``
        + one ``# TYPE`` per family (the seed's hand-rolled formatter
        repeated ``# TYPE`` before every labeled sample)."""
        snap = self.snapshot()
        reg = MetricsRegistry("deepdfa_serve_")
        reg.counter("requests_total",
                    "Every /score request received").set(
            snap["requests_total"])
        responses = reg.counter("responses_total",
                                "Responses by HTTP status", labels=("code",))
        for code, n in snap["responses_total"].items():
            responses.set(n, code=code)
        reg.counter("errors_total", "4xx/5xx responses").set(
            snap["errors_total"])
        reg.counter("dropped_total",
                    "Requests rejected by admission control").set(
            snap["dropped_total"])
        reg.counter("batches_total", "Dispatched micro-batches").set(
            snap["batches_total"])
        reg.counter("batch_graphs_total",
                    "Real graphs in dispatched batches").set(
            snap["batch_graphs_total"])
        reg.gauge("batch_occupancy_mean",
                  "Mean real-graphs / bucket-capacity per batch").set(
            snap["mean_batch_occupancy"])
        reg.gauge("queue_depth",
                  "Requests waiting in the micro-batch queue").set(
            snap["queue_depth"])
        reg.gauge("inflight", "/score requests currently in flight").set(
            snap["inflight"])
        if snap["padding_efficiency"]:
            pad = reg.gauge(
                "padding_efficiency",
                "Cumulative real / padded fraction of dispatched batch "
                "budgets per bucket (axis: nodes, edges, graphs)",
                labels=("bucket", "axis"))
            for bucket, axes in snap["padding_efficiency"].items():
                for axis, value in axes.items():
                    pad.set(value, bucket=bucket, axis=axis)
        reg.counter("cascade_escalated_total",
                    "Borderline tier-1 scores escalated to tier 2").set(
            snap["cascade_escalated_total"])
        reg.counter("cascade_degraded_total",
                    "Escalations degraded back to the tier-1 answer "
                    "(queue full / deadline blown / tier-2 failure — "
                    "invariant 24, never a 5xx)").set(
            snap["cascade_degraded_total"])
        answered = reg.counter("cascade_answered_total",
                               "Served /score rows by answering tier",
                               labels=("tier",))
        for tier, n in snap["cascade_answered"].items():
            answered.set(n, tier=tier)
        reg.gauge("tier2_queue_depth",
                  "Escalations waiting in the tier-2 queue").set(
            snap["tier2_queue_depth"])
        reg.gauge("frontend_queue_depth",
                  "Sources waiting in the frontend encode queue").set(
            snap["frontend_queue_depth"])
        reg.counter("frontend_inline_total",
                    "Cold requests encoded inline because the frontend "
                    "pool was unavailable (degrade-to-inline, invariant "
                    "25 — never a 5xx)").set(
            snap["frontend_inline_total"])
        admitted = reg.counter("admission_admitted_total",
                               "Requests admitted past admission control, "
                               "by priority class", labels=("class",))
        for klass, n in snap["admission_admitted"].items():
            admitted.set(n, **{"class": klass})
        shed = reg.counter("admission_shed_total",
                           "Requests shed by admission control (429 + "
                           "deterministic Retry-After, never a 5xx), "
                           "by priority class", labels=("class",))
        for klass, n in snap["admission_shed"].items():
            shed.set(n, **{"class": klass})
        reg.gauge("brownout_level",
                  "Current brownout degradation level (0 normal, 1 shed "
                  "batch, 2 + cache hits + tier-1 only, 3 + shed "
                  "interactive)").set(snap["brownout_level"])
        reg.counter("brownout_transitions_total",
                    "Brownout level transitions (each one journaled as a "
                    "brownout_transition event)").set(
            snap["brownout_transitions_total"])
        reg.counter("brownout_suppressed_escalations_total",
                    "Cascade escalations suppressed at brownout level >= 2 "
                    "(tier-1 only — the tier-1 answer is still served)").set(
            snap["brownout_suppressed_escalations_total"])
        for family, help_, reservoir in (
                ("latency_ms", "End-to-end /score latency", self.latency),
                ("queue_wait_ms", "Time a graph waited in the micro-batch "
                                  "queue", self.queue_wait),
                ("dispatch_ms", "Engine dispatch wall time per batch",
                 self.dispatch),
                ("tier1_latency_ms", "Tier-1 (GGNN) per-row score latency",
                 self.tier1_latency),
                ("tier2_latency_ms", "Tier-2 escalate-to-answer latency",
                 self.tier2_latency),
                ("tier2_queue_wait_ms", "Time an escalation waited in the "
                                        "tier-2 queue", self.tier2_queue_wait),
                ("tier2_dispatch_ms", "Joint-engine dispatch wall time per "
                                      "tier-2 window", self.tier2_dispatch),
                ("frontend_encode_ms", "Frontend pool encode wall time per "
                                       "source", self.frontend_encode),
                ("frontend_queue_wait_ms", "Time a source waited in the "
                                           "frontend encode queue",
                 self.frontend_queue_wait)):
            fam = reg.gauge(family, f"{help_} (windowed quantiles)",
                            labels=("quantile",))
            for q in (0.50, 0.99):
                fam.set(reservoir.quantile(q), quantile=q)
        warm = snap.get("warmup")
        if warm:
            reg.counter("warm_store_hits_total",
                        "Warm-store program hits at warmup").set(
                warm.get("hits"))
            reg.counter("warm_store_misses_total",
                        "Warm-store misses at warmup").set(warm.get("misses"))
            reg.gauge("warm_store_compile_seconds_saved",
                      "Compile seconds skipped via warm-store hits").set(
                warm.get("compile_seconds_saved"))
            compile_s = reg.gauge("warmup_compile_seconds",
                                  "Per-bucket warmup compile seconds",
                                  labels=("bucket", "source"))
            for bucket, row in (warm.get("per_bucket") or {}).items():
                compile_s.set(row.get("compile_seconds"), bucket=bucket,
                              source=row.get("source"))
        if cache_stats:
            reg.counter("cache_hits_total", "Scan-cache result hits").set(
                cache_stats.get("hits"))
            reg.counter("cache_encode_hits_total",
                        "Scan-cache encoded-graph hits").set(
                cache_stats.get("encode_hits"))
            reg.counter("cache_misses_total", "Scan-cache misses").set(
                cache_stats.get("misses"))
            reg.counter("cache_evictions_total", "Scan-cache evictions").set(
                cache_stats.get("evictions"))
            reg.gauge("cache_entries", "Scan-cache entries").set(
                cache_stats.get("entries"))
            reg.gauge("cache_hit_rate", "Scan-cache hit rate").set(
                cache_stats.get("hit_rate"))
        tracer = self.tracer
        if tracer is not None:
            reg.counter("trace_spans_total",
                        "Spans recorded by this replica's tracer").set(
                tracer.recorded_total)
            reg.counter("trace_spans_dropped_total",
                        "Spans lost at export (never fatal)").set(
                tracer.dropped_total)
        drift = self.drift
        if drift is not None:
            psi_g = reg.gauge("score_drift",
                              "PSI of the sliding score window vs the "
                              "model rev's reference window",
                              labels=("model_rev",))
            alert_g = reg.gauge("score_drift_alert",
                                "1 when score_drift crossed the configured "
                                "threshold", labels=("model_rev",))
            hist = reg.histogram(
                "score", "Current-window score distribution",
                buckets=[round((i + 1) / drift.bins, 6)
                         for i in range(drift.bins)],
                labels=("model_rev",))
            for rev, row in drift.snapshot().items():
                psi_g.set(row["psi"], model_rev=rev)
                alert_g.set(int(row["alert"]), model_rev=rev)
                hist.set_histogram(row["current_counts"], row["current_sum"],
                                   row["current_n"], model_rev=rev)
            reg.counter("score_drift_evicted_revs_total",
                        "model_revs LRU-evicted from the drift sentinel "
                        "(bounded /metrics cardinality)").set(
                drift.evicted_revs_total)
        flight = self.flight
        if flight is not None:
            reg.counter(
                "obs_dropped_total",
                "Flight-recorder events dropped instead of failing the "
                "request they annotate (invariant 14)").set(
                flight.dropped_total)
        return reg.render()
