"""Dynamic micro-batching over the scoring engine.

The throughput lever for small-graph GNN serving is batching policy (DGL
paper / GNN-acceleration survey, PAPERS.md): a ~50-node CFG nowhere near
saturates the device, so the server must coalesce concurrent requests
into one padded dispatch. Policy here:

- requests enter a **bounded** queue (``max_queue``) — beyond it,
  :class:`QueueFullError` (the server turns that into 503 backpressure;
  an unbounded queue converts overload into unbounded latency);
- a single dispatcher thread wakes on the first queued request, then
  waits until ``max_batch`` requests are pending or ``max_wait_ms`` has
  elapsed since that first request (classic size-or-deadline window);
- the drained window is grouped by the engine's size buckets and each
  group greedy-packed into batches within the bucket's budgets, so one
  window can dispatch several shapes without mixing them.

One dispatcher thread is deliberate: the engine's compiled callables
serialize on the device anyway, and a single thread keeps batch formation
deterministic under test. Engine failures (including the injected
``serve.engine_raises``) fail the requests *of that batch* via their
futures and the loop continues — a poisoned request must never kill the
server. ``stop(drain=True)`` refuses new work and drains what's queued,
which is what SIGTERM maps to.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field

from .engine import ScoringEngine, ServeBucket

__all__ = ["QueueFullError", "MicroBatcher"]


class QueueFullError(RuntimeError):
    """Admission control: the bounded request queue is at capacity."""


@dataclass
class _Pending:
    graph: object
    bucket: ServeBucket
    future: Future = field(default_factory=Future)
    # tracing handoff: the submitting request's span context and enqueue
    # wall time, so the dispatcher thread can close the queue.wait span
    # against the right trace
    ctx: object = None
    enqueued_s: float = 0.0


class MicroBatcher:
    def __init__(self, engine: ScoringEngine, max_batch: int = 16,
                 max_wait_ms: float = 5.0, max_queue: int = 128,
                 metrics=None, tracer=None):
        self.engine = engine
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_ms) / 1000.0
        self.max_queue = int(max_queue)
        self.metrics = metrics
        self.tracer = tracer
        self._pending: list[_Pending] = []
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._stopping = False
        self._thread = threading.Thread(
            target=self._run, name="serve-batcher", daemon=True)
        self._started = False

    # -- client side --------------------------------------------------------

    def start(self) -> "MicroBatcher":
        if not self._started:
            self._started = True
            self._thread.start()
        return self

    def submit(self, graph) -> Future:
        """Route + enqueue one graph; the Future resolves to its function
        probability. Raises :class:`QueueFullError` (backpressure),
        :class:`~.engine.OversizeGraphError` (no bucket), or RuntimeError
        once draining."""
        bucket = self.engine.assign_bucket(graph)  # raises OversizeGraphError
        item = _Pending(graph=graph, bucket=bucket,
                        ctx=(self.tracer.current()
                             if self.tracer is not None else None),
                        enqueued_s=time.time())
        with self._wake:
            if self._stopping:
                raise RuntimeError("batcher is draining — not accepting work")
            if len(self._pending) >= self.max_queue:
                raise QueueFullError(
                    f"request queue at capacity ({self.max_queue})")
            self._pending.append(item)
            if self.metrics is not None:
                self.metrics.set_gauge("queue_depth", len(self._pending))
            self._wake.notify_all()
        return item.future

    def stop(self, drain: bool = True, timeout: float | None = None) -> None:
        """Refuse new submissions; with ``drain`` wait for queued requests
        to resolve (bounded by ``timeout``), else fail them immediately."""
        with self._wake:
            self._stopping = True
            if not drain:
                for item in self._pending:
                    item.future.set_exception(
                        RuntimeError("server shutting down"))
                self._pending.clear()
            self._wake.notify_all()
        if self._started:
            self._thread.join(timeout=timeout)

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._pending)

    # -- dispatcher side ----------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._wake:
                while not self._pending and not self._stopping:
                    self._wake.wait()
                if not self._pending and self._stopping:
                    return
            # size-or-deadline window, measured from the first request
            deadline = time.monotonic() + self.max_wait_s
            with self._wake:
                while (len(self._pending) < self.max_batch
                       and not self._stopping):
                    remain = deadline - time.monotonic()
                    if remain <= 0:
                        break
                    self._wake.wait(timeout=remain)
                window, self._pending = self._pending, []
                if self.metrics is not None:
                    self.metrics.set_gauge("queue_depth", 0)
            self._dispatch_window(window)

    def _dispatch_window(self, window: list[_Pending]) -> None:
        assembled_s = time.time()
        by_bucket: dict[ServeBucket, list[_Pending]] = {}
        for item in window:
            by_bucket.setdefault(item.bucket, []).append(item)
        # chunks of n_replicas packed batches go down as ONE dispatch on
        # mesh-replicated engines (one batch per device); single-replica
        # engines degrade to the per-batch loop unchanged
        chunk = max(1, self.engine.n_replicas)
        plans = [(bucket, self._pack(bucket, items))
                 for bucket, items in by_bucket.items()]
        if self.tracer is not None and window:
            parent = next((i.ctx for i in window if i.ctx is not None), None)
            self.tracer.record("batch.assembly", assembled_s, parent=parent,
                               n_graphs=len(window),
                               n_buckets=len(by_bucket))
        for bucket, packed in plans:
            for i in range(0, len(packed), chunk):
                self._dispatch(bucket, packed[i:i + chunk])

    def _pack(self, bucket: ServeBucket, items: list[_Pending]):
        """Greedy-fill within the bucket's graph/node/edge budgets (the
        GraphBatcher discipline, applied to request groups)."""
        out, nn, ne = [], 0, 0
        cur: list[_Pending] = []
        cap = min(bucket.capacity, self.max_batch)
        for item in items:
            g = item.graph
            if cur and (len(cur) >= cap
                        or not bucket.spec.fits(
                            len(cur) + 1, nn + g.n_nodes, ne + g.n_edges)):
                out.append(cur)
                cur, nn, ne = [], 0, 0
            cur.append(item)
            nn += g.n_nodes
            ne += g.n_edges
        if cur:
            out.append(cur)
        return out

    def _dispatch(self, bucket: ServeBucket,
                  batches: list[list[_Pending]]) -> None:
        tracer, now = self.tracer, time.time()
        n_real = sum(len(b) for b in batches)
        first_ctx = None
        for b in batches:
            for item in b:
                if first_ctx is None and item.ctx is not None:
                    first_ctx = item.ctx
                if item.enqueued_s:
                    if self.metrics is not None:
                        self.metrics.queue_wait.observe(
                            (now - item.enqueued_s) * 1e3)
                    if tracer is not None:
                        tracer.record("queue.wait", item.enqueued_s, now,
                                      parent=item.ctx,
                                      bucket=bucket.capacity)
        t0 = time.time()
        try:
            results = self.engine.score_groups(
                [[i.graph for i in b] for b in batches], bucket)
        except Exception as exc:  # noqa: BLE001 — per-chunk failure domain
            if tracer is not None:
                tracer.record("engine.dispatch", t0, parent=first_ctx,
                              n_graphs=n_real, error=type(exc).__name__)
            for b in batches:
                for item in b:
                    item.future.set_exception(exc)
            return
        t1 = time.time()
        if self.metrics is not None:
            self.metrics.dispatch.observe((t1 - t0) * 1e3)
            self.metrics.record_dispatch_interval(t0, t1)
        if tracer is not None:
            tracer.record("engine.dispatch", t0, t1, parent=first_ctx,
                          n_graphs=n_real, n_batches=len(batches),
                          bucket=bucket.capacity)
        for b, probs in zip(batches, results):
            if self.metrics is not None:
                self.metrics.observe_batch(len(b), bucket.capacity)
                self.metrics.observe_padding(
                    bucket.graph_nodes,
                    real={"nodes": sum(i.graph.n_nodes for i in b),
                          "edges": sum(i.graph.n_edges for i in b),
                          "graphs": len(b)},
                    padded={"nodes": bucket.spec.max_nodes,
                            "edges": bucket.spec.max_edges,
                            "graphs": bucket.spec.max_graphs})
            for item, p in zip(b, probs):
                item.future.set_result(float(p))
        if tracer is not None:
            tracer.record("host.reduce", t1, parent=first_ctx,
                          n_graphs=n_real)
