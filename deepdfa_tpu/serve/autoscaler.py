"""Self-healing SLO-driven fleet autoscaler: the first closed-loop
actuator in the system — observability stops being read-only and starts
steering capacity.

The :class:`Autoscaler` supervises a set of ScoreServer replicas behind a
:class:`~deepdfa_tpu.serve.router.FleetRouter`. Each poll it

1. **heals** — a replica whose process died (``kill -9``, OOM) is
   deregistered from the ring and replaced; the replacement warm-joins
   through the warm store (invariant 11: ``join_cold_compiles == 0``)
   and enters the ring only after the router's readiness probe finds it
   warm. Healing is not subject to the scale cooldown — a dead replica
   is replaced immediately, within ``serve.autoscale.replace_deadline_s``;
2. **observes** — scrapes every live replica's ``/slo`` and takes the
   worst fast-window burn rate as the fleet's load signal;
3. **decides** — hysteresis watermarks (``burn_high``/``burn_low``) with
   consecutive-poll streaks and a post-action cooldown, so burn-rate
   flapping never oscillates the fleet; replica count is clamped to
   ``[min_replicas, max_replicas]``.

Actuation honours the manual-operation protocol (standing invariant 22):
scale-down drains via the replica's flag-only SIGTERM path (invariants
6/12) after leaving the ring — the autoscaler never hard-kills a healthy
replica; scale-up admits a replica only after its warm join, never a cold
one. Spawns retry with deterministic backoff through
:mod:`deepdfa_tpu.resilience.retry`; exhaustion journals a give-up.

Every decision is journaled as an ``autoscale_transition`` event and
mirrored into the crash flight ring (invariant 20: neither sink may fail
the decision it annotates).

Chaos points (``DEEPDFA_FAULTS``): ``autoscale.spawn_fail`` fails a
launch inside the retry loop; ``autoscale.replica_crash`` kill -9's one
managed replica mid-load, driving the heal path deterministically.
"""

from __future__ import annotations

import http.client
import json
import logging
import re
import signal
import subprocess
import threading
import time
from collections import deque

from deepdfa_tpu.config import AutoscaleConfig
from deepdfa_tpu.resilience import faults
from deepdfa_tpu.resilience.retry import RetryExhausted, RetryPolicy, retry_call

__all__ = [
    "SpawnError",
    "SubprocessReplica",
    "SubprocessLauncher",
    "AdminRouterClient",
    "Autoscaler",
    "max_fast_burn",
]

logger = logging.getLogger(__name__)

SCRAPE_TIMEOUT_S = 5.0

_SAMPLE_RE = re.compile(r"slo_burn_rate\{([^}]*)\}\s+(\S+)")
_LABEL_RE = re.compile(r'(\w+)="([^"]*)"')


def max_fast_burn(text: str) -> float | None:
    """Worst fast-window burn rate in one ``/slo`` exposition body; None
    when the scrape carries no finite fast-window sample yet."""
    best = None
    for m in _SAMPLE_RE.finditer(text or ""):
        labels = dict(_LABEL_RE.findall(m.group(1)))
        if labels.get("window") != "fast":
            continue
        try:
            value = float(m.group(2))
        except ValueError:
            continue
        if value != value:  # NaN: window has no samples yet
            continue
        if best is None or value > best:
            best = value
    return best


class SpawnError(RuntimeError):
    """A replica launch failed before its serving line (retryable)."""


class SubprocessReplica:
    """One launched replica process: the handle the autoscaler manages.

    ``drain()`` is the flag-only SIGTERM path (invariants 6/12) — the
    replica finishes in-flight work and exits on its own; ``kill()`` is
    SIGKILL and exists for chaos only."""

    def __init__(self, proc, host: str, port: int, serving: dict):
        self.proc = proc
        self.host = host
        self.port = int(port)
        self.name = f"{host}:{port}"
        self.serving = dict(serving)
        warm = self.serving.get("warm_store") or {}
        # invariant 11: a warm join reports zero store misses
        self.join_cold_compiles = warm.get("misses")

    def poll(self) -> int | None:
        """Exit code when the process has died, else None."""
        return self.proc.poll()

    def drain(self) -> None:
        if self.proc.poll() is None:
            self.proc.send_signal(signal.SIGTERM)

    def kill(self) -> None:
        if self.proc.poll() is None:
            self.proc.kill()

    def wait(self, timeout: float | None = None) -> int:
        return self.proc.wait(timeout)


class SubprocessLauncher:
    """Spawns replica subprocesses and blocks until each prints its
    ``{"status": "serving", ...}`` line (the serve CLI contract), which
    carries the bound port and the warm-store join report."""

    def __init__(self, build_argv, host: str = "127.0.0.1", env=None,
                 startup_timeout_s: float = 120.0):
        # build_argv(index) -> argv for the index-th launch, or a static argv
        self._build_argv = build_argv
        self._host = host
        self._env = env
        self._startup_timeout_s = float(startup_timeout_s)
        self._spawned = 0

    def spawn(self) -> SubprocessReplica:
        argv = (self._build_argv(self._spawned)
                if callable(self._build_argv) else list(self._build_argv))
        proc = subprocess.Popen(argv, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True,
                                env=self._env)
        serving: dict = {}
        found = threading.Event()
        tail: deque[str] = deque(maxlen=50)

        def _scan_stdout():
            # keeps draining after the serving line so the pipe never fills
            for line in proc.stdout:
                tail.append(line.rstrip())
                if not found.is_set():
                    try:
                        obj = json.loads(line)
                    except (json.JSONDecodeError, ValueError):
                        continue
                    if isinstance(obj, dict) and obj.get("status") == "serving":
                        serving.update(obj)
                        found.set()

        threading.Thread(target=_scan_stdout, name="replica-stdout",
                         daemon=True).start()
        if not found.wait(self._startup_timeout_s):
            proc.kill()
            raise SpawnError(
                "replica never printed its serving line "
                f"(exit={proc.poll()}, tail={list(tail)[-5:]})")
        self._spawned += 1
        host = serving.get("host") or self._host
        return SubprocessReplica(proc, host, serving["port"], serving)


class AdminRouterClient:
    """HTTP twin of :class:`FleetRouter`'s membership surface
    (``/admin/backends``), for an autoscaler running outside the router
    process. Duck-compatible with the in-process router: ``add_backend``,
    ``remove_backend``, ``probe_once``."""

    def __init__(self, host: str, port: int, timeout_s: float = 5.0):
        self.host = host
        self.port = int(port)
        self.timeout_s = float(timeout_s)

    def _request(self, method: str, path: str, payload=None) -> dict:
        body = None if payload is None else json.dumps(payload).encode()
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout_s)
        try:
            conn.request(method, path, body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            data = resp.read()
        finally:
            conn.close()
        return json.loads(data or b"{}")

    def add_backend(self, spec) -> dict:
        return self._request("POST", "/admin/backends",
                             {"action": "add", "backend": str(spec)})

    def remove_backend(self, name: str) -> bool:
        out = self._request("POST", "/admin/backends",
                            {"action": "remove", "backend": str(name)})
        return bool(out.get("removed"))

    def probe_once(self) -> dict:
        out = self._request("GET", "/admin/backends")
        return {name: info.get("state")
                for name, info in (out.get("backends") or {}).items()}


class Autoscaler:
    """The decision loop. ``router`` needs ``add_backend`` /
    ``remove_backend`` / ``probe_once`` (a :class:`FleetRouter` or an
    :class:`AdminRouterClient`); ``launcher`` needs ``spawn() -> handle``
    where a handle has ``name/host/port/join_cold_compiles/poll/drain/
    kill``. ``scrape``, ``clock`` and ``sleep`` are injectable so the
    unit battery drives a virtual clock."""

    def __init__(self, cfg: AutoscaleConfig, router, launcher,
                 journal=None, flight=None, scrape=None,
                 clock=time.monotonic, sleep=time.sleep):
        self._cfg = cfg
        self._router = router
        self._launcher = launcher
        self._journal = journal
        self._flight = flight
        self._scrape = scrape or self._scrape_slo
        self._clock = clock
        self._sleep = sleep
        # one lock guards all decision state: the poll loop runs on its
        # own thread while summary()/stop() read from the caller's
        # (the analysis unguarded-state pass holds this at every commit)
        self._lock = threading.Lock()
        self._replicas: dict[str, object] = {}  # name -> live handle
        self._drained: list = []  # handles we SIGTERM'd, awaiting exit
        self._decisions: list[dict] = []
        self._streak_up = 0
        self._streak_down = 0
        self._last_action_t: float | None = None
        self._t0 = clock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "Autoscaler":
        self.ensure_min()
        self._thread = threading.Thread(target=self._run, name="autoscaler",
                                        daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self._cfg.poll_interval_s):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — the supervisor never dies
                logger.exception("autoscale poll failed; continuing")

    def stop(self, drain: bool = True) -> dict:
        """Stop the loop; optionally drain every managed replica (ring
        exit first, then flag-only SIGTERM). Returns :meth:`summary`."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        if drain:
            with self._lock:
                handles = list(self._replicas.items())
                self._replicas = {}
            for name, handle in handles:
                self._router.remove_backend(name)
                handle.drain()
                with self._lock:
                    self._drained.append(handle)
        return self.summary()

    def adopt(self, handle) -> None:
        """Take over supervision of an already-running replica (the bench
        hands the autoscaler its baseline fleet this way)."""
        with self._lock:
            self._replicas[handle.name] = handle
        self._router.add_backend(handle.name)

    # -- one decision-loop tick ---------------------------------------------

    def poll_once(self) -> list[dict]:
        """One supervisor tick: chaos, heal, min-clamp, observe, decide.
        Returns the decisions made this tick."""
        made: list[dict] = []
        made += self._maybe_inject_crash()
        made += self._heal()
        made += self.ensure_min()
        made += self._decide_scale(self._observe_burn())
        return made

    def _maybe_inject_crash(self) -> list[dict]:
        # seed-deterministic chaos: kill -9 one managed replica mid-load,
        # proving detection + ring failover + warm replacement end to end
        if not faults.fire("autoscale.replica_crash"):
            return []
        with self._lock:
            handle = next(reversed(list(self._replicas.values())), None)
        if handle is None:
            return []
        handle.kill()
        return [self._record("replica_crash_injected", backend=handle.name)]

    def _heal(self) -> list[dict]:
        with self._lock:
            snapshot = list(self._replicas.items())
        made = []
        for name, handle in snapshot:
            code = handle.poll()
            if code is None:
                continue
            t_detect = self._clock()
            logger.warning("replica %s died (exit %s) — replacing", name, code)
            self._router.remove_backend(name)
            with self._lock:
                self._replicas.pop(name, None)
            new = self._spawn_replica(reason=f"replace:{name}")
            fields = {"backend": name, "exit_code": code}
            if new is not None:
                fields.update(
                    replacement=new.name,
                    replace_latency_s=round(self._clock() - t_detect, 3),
                    join_cold_compiles=new.join_cold_compiles)
            made.append(self._record("replace", **fields))
        return made

    def ensure_min(self) -> list[dict]:
        """Spawn until ``min_replicas`` live replicas exist (startup and
        after give-ups); not subject to the cooldown."""
        made = []
        while True:
            with self._lock:
                n = len(self._replicas)
            if n >= self._cfg.min_replicas:
                break
            handle = self._spawn_replica(reason="min_replicas")
            if handle is None:
                break  # give-up already recorded; retry next tick
            made.append(self._record(
                "scale_up", reason="min_replicas", backend=handle.name,
                replicas=n + 1,
                join_cold_compiles=handle.join_cold_compiles))
        return made

    def _observe_burn(self) -> float | None:
        with self._lock:
            handles = list(self._replicas.values())
        burns = []
        for handle in handles:
            burn = self._scrape(handle)
            if burn is not None:
                burns.append(burn)
        return max(burns, default=None)

    def _scrape_slo(self, handle) -> float | None:
        try:
            conn = http.client.HTTPConnection(handle.host, handle.port,
                                              timeout=SCRAPE_TIMEOUT_S)
            try:
                conn.request("GET", "/slo")
                text = conn.getresponse().read().decode()
            finally:
                conn.close()
        except OSError:
            return None  # dead/draining replica: the heal path owns it
        return max_fast_burn(text)

    def _decide_scale(self, burn: float | None) -> list[dict]:
        if burn is None:
            return []
        now = self._clock()
        cfg = self._cfg
        with self._lock:
            # hysteresis: streaks advance only outside the dead band, and
            # any excursion into the opposite band resets the other side
            if burn >= cfg.burn_high:
                self._streak_up += 1
                self._streak_down = 0
            elif burn <= cfg.burn_low:
                self._streak_down += 1
                self._streak_up = 0
            else:
                self._streak_up = 0
                self._streak_down = 0
            up = self._streak_up >= cfg.up_consecutive
            down = self._streak_down >= cfg.down_consecutive
            cooling = (self._last_action_t is not None
                       and now - self._last_action_t < cfg.cooldown_s)
            n = len(self._replicas)
        if cooling or not (up or down):
            return []
        if up:
            if n >= cfg.max_replicas:
                self._reset_streaks()
                return [self._record("hold", reason="max_replicas",
                                     burn=round(burn, 3), replicas=n)]
            return [self._scale_up(burn, n)]
        if n <= cfg.min_replicas:
            self._reset_streaks()
            return [self._record("hold", reason="min_replicas",
                                 burn=round(burn, 3), replicas=n)]
        return [self._scale_down(burn, n)]

    def _reset_streaks(self, acted: bool = False) -> None:
        with self._lock:
            self._streak_up = 0
            self._streak_down = 0
            if acted:
                self._last_action_t = self._clock()

    def _scale_up(self, burn: float, n: int) -> dict:
        handle = self._spawn_replica(reason=f"burn={burn:.2f}")
        self._reset_streaks(acted=True)
        if handle is None:
            return self._decisions_tail()
        return self._record(
            "scale_up", reason="burn_high", burn=round(burn, 3),
            backend=handle.name, replicas=n + 1,
            join_cold_compiles=handle.join_cold_compiles)

    def _scale_down(self, burn: float, n: int) -> dict:
        # newest replica first (LIFO): the baseline fleet survives swings
        with self._lock:
            items = list(self._replicas.items())
            if not items:
                return {}
            name, handle = items[-1]
            del self._replicas[name]
        # ring exit first — its keyspace slides to neighbours while the
        # replica finishes in-flight work under the flag-only drain
        self._router.remove_backend(name)
        handle.drain()
        with self._lock:
            self._drained.append(handle)
        self._reset_streaks(acted=True)
        return self._record("scale_down", reason="burn_low",
                            burn=round(burn, 3), backend=name,
                            replicas=n - 1)

    # -- spawning ------------------------------------------------------------

    def _spawn_replica(self, reason: str):
        cfg = self._cfg

        def attempt():
            faults.raise_if("autoscale.spawn_fail")
            return self._launcher.spawn()

        policy = RetryPolicy(attempts=cfg.spawn_attempts,
                             base_delay=cfg.spawn_backoff_s,
                             deadline=cfg.replace_deadline_s)
        try:
            handle = retry_call(
                attempt, policy=policy, sleep=self._sleep, clock=self._clock,
                on_retry=lambda n, exc, delay: logger.warning(
                    "spawn attempt %d failed (%s); retrying in %.2fs",
                    n, type(exc).__name__, delay))
        except RetryExhausted as exc:
            self._record("spawn_give_up", reason=reason,
                         attempts=exc.attempts, error=str(exc.last))
            return None
        self._router.add_backend(handle.name)
        with self._lock:
            self._replicas[handle.name] = handle
        if not self._wait_ready(handle.name):
            logger.warning("replica %s not ready within deadline", handle.name)
        return handle

    def _wait_ready(self, name: str) -> bool:
        """Block until the router's readiness probe admits ``name`` (warm
        healthz), bounded by ``replace_deadline_s``."""
        deadline = self._clock() + self._cfg.replace_deadline_s
        while True:
            states = self._router.probe_once()
            if states.get(name) == "ready":
                return True
            if self._clock() >= deadline:
                return False
            self._sleep(0.05)

    # -- observability -------------------------------------------------------

    def _record(self, action: str, **fields) -> dict:
        decision = {"action": action,
                    "t": round(self._clock() - self._t0, 3), **fields}
        with self._lock:
            self._decisions.append(decision)
        if self._journal is not None:
            try:
                self._journal.write(event="autoscale_transition", **decision)
            except Exception:  # noqa: BLE001 — invariant 20: sinks never
                logger.warning("autoscale journal write dropped")
        if self._flight is not None:
            self._flight.record("autoscale.transition", **decision)
        logger.info("autoscale decision: %s", decision)
        return decision

    def _decisions_tail(self) -> dict:
        with self._lock:
            return dict(self._decisions[-1]) if self._decisions else {}

    def summary(self) -> dict:
        """The bench/artifact view: every decision plus the gate
        aggregates (worst replacement latency, join compiles, give-ups)."""
        with self._lock:
            decisions = [dict(d) for d in self._decisions]
            replicas = sorted(self._replicas)
        latencies = [d["replace_latency_s"] for d in decisions
                     if d.get("replace_latency_s") is not None]
        joins = [d["join_cold_compiles"] for d in decisions
                 if d.get("join_cold_compiles") is not None]
        return {
            "replicas": replicas,
            "decisions": decisions,
            "scale_decisions": len(decisions),
            "replace_latency_s": max(latencies) if latencies else None,
            "replacements": sum(d["action"] == "replace" for d in decisions),
            "join_cold_compiles": sum(joins) if joins else 0,
            "spawn_give_ups": sum(d["action"] == "spawn_give_up"
                                  for d in decisions),
        }
