"""Stdlib HTTP JSON scoring service — the long-lived online surface.

Endpoints:

- ``POST /score``  ``{"source": "<C text>"}`` → per-function rows
  ``{"function", "vulnerable_probability"}`` (or ``{"function","error"}``
  for functions with no scoreable CFG). Repeat scans of the same
  normalized source are served from the content-addressed cache
  (``"cached": true``) without touching the frontend.
- ``GET /healthz`` → liveness. Stays green through per-request failures
  (frontend errors, injected engine faults) — only process death or
  drain takes it away.
- ``GET /metrics`` → Prometheus text: queue depth, batch occupancy,
  cache hit rate, p50/p99 latency (see :mod:`.metrics`).

Failure domains, smallest first: a bad request body is a 400; an
unparseable source is a 422; an oversize function a 413; admission
control (bounded queue) and the ``serve.drop_request`` fault are 503;
an engine failure (``serve.engine_raises`` included) is a 500 for the
requests in that batch. None of them touch the server's lifetime.

Shutdown: SIGTERM/SIGINT set a flag; ``/score`` starts refusing with
503, the micro-batcher drains what is queued, in-flight handler threads
finish writing their responses (bounded by ``serve.drain_timeout_s``),
then the listener closes. No request that got a 200-path admission is
abandoned mid-flight.
"""

from __future__ import annotations

import json
import logging
import signal
import threading
import time
from concurrent.futures import TimeoutError as _FutureTimeout
from contextlib import nullcontext
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

from deepdfa_tpu.config import ExperimentConfig, ServeConfig
from deepdfa_tpu.obs import (
    FlightRecorder,
    ScoreDriftSentinel,
    SLOEngine,
    Tracer,
    parse_traceparent,
    serve_specs,
    write_alerts_artifact,
)
from deepdfa_tpu.obs.flightrec import install_sigusr2
from deepdfa_tpu.pipeline import encode_source, load_vocabs, source_key
from deepdfa_tpu.resilience import faults

from .admission import QOS_CLASSES, AdmissionController, BrownoutController
from .batcher import MicroBatcher, QueueFullError
from .cache import ScanCache
from .engine import OversizeGraphError, ScoringEngine
from .frontend import ENCODE_ITEM_ERRORS, FrontendPool
from .metrics import ServeMetrics

__all__ = ["ScoreServer", "build_server", "serve_command", "main"]

logger = logging.getLogger(__name__)

REQUEST_TIMEOUT_S = 60.0  # cap on one request's wait for its batch scores


class ScoreServer:
    """Engine + vocabs + cache + batcher behind a ThreadingHTTPServer."""

    def __init__(self, engine: ScoringEngine, vocabs,
                 cfg: ServeConfig | None = None, cache: ScanCache | None = None,
                 metrics: ServeMetrics | None = None,
                 replica_id: str | None = None, warm_store=None,
                 journal=None, tier2_engine=None, frontend_pool=None,
                 vocab_source=None):
        self.cfg = cfg or ServeConfig()
        self.engine = engine
        self.vocabs = vocabs
        self.replica_id = replica_id or self.cfg.replica_id
        self.warm_store = warm_store
        self.journal = journal
        self.metrics = metrics or ServeMetrics(self.cfg.latency_window)
        self.cache = cache if cache is not None else ScanCache(
            self.cfg.cache_entries)
        obs = self.cfg.obs
        self.tracer = Tracer(
            proc="serve", max_spans=obs.trace_buffer,
            slow_ms=(obs.slow_trace_ms
                     if obs.slow_trace_ms and obs.slow_trace_ms > 0
                     else None),
            exemplar_dir=obs.trace_dir, max_exemplars=obs.max_exemplars,
        ) if obs.trace else None
        self.drift = ScoreDriftSentinel(
            window=obs.drift_window, bins=obs.drift_bins,
            threshold=obs.drift_threshold,
            min_samples=obs.drift_min_samples,
            max_revs=obs.drift_max_revs)
        self.flight = FlightRecorder(
            capacity=obs.flight_events, proc="serve",
            dump_dir=obs.flight_dir)
        cascade_cfg = self.cfg.cascade
        self.slo = SLOEngine(
            serve_specs(availability=obs.slo_availability,
                        error_rate=obs.slo_error_rate,
                        p99_ms=obs.slo_p99_ms,
                        # tier 2 gets its own deadline budget as the SLO
                        # ceiling: sustained waits at the degradation
                        # boundary are an incident before degradations are
                        tier2_p99_ms=(cascade_cfg.tier2_deadline_ms
                                      if cascade_cfg.enabled else None)),
            fast_window_s=obs.slo_fast_window_s,
            slow_window_s=obs.slo_slow_window_s,
            burn_threshold=obs.slo_burn_threshold,
            flight=self.flight)
        # (responses_total, monotonic time it last changed) — the idle
        # detector behind _slo_snapshot's stale-latency suppression
        self._slo_traffic_mark = (0, time.monotonic())
        self.alerts_path = Path(obs.alerts_path) if obs.alerts_path else None
        self.metrics.tracer = self.tracer
        self.metrics.drift = self.drift
        self.metrics.flight = self.flight
        if hasattr(engine, "flight"):
            engine.flight = self.flight
        self.batcher = MicroBatcher(
            engine, max_batch=self.cfg.max_batch,
            max_wait_ms=self.cfg.max_wait_ms, max_queue=self.cfg.max_queue,
            metrics=self.metrics, tracer=self.tracer).start()
        # tier-2 escalation plane (serve/cascade.py): band routing over a
        # second bounded queue feeding the joint LLM+GNN engine
        self.cascade = None
        if cascade_cfg.enabled:
            if tier2_engine is None:
                if not cascade_cfg.joint_dir:
                    raise ValueError(
                        "serve.cascade.enabled needs a tier-2 engine: pass "
                        "tier2_engine= or set serve.cascade.joint_dir to a "
                        "train_joint.py run dir")
                from deepdfa_tpu.llm.joint_engine import JointEngine

                tier2_engine = JointEngine.from_run_dir(
                    cascade_cfg.joint_dir,
                    max_batch=cascade_cfg.tier2_max_batch)
            from .cascade import CascadeRouter

            self.cascade = CascadeRouter(
                cascade_cfg, tier2_engine,
                metrics=self.metrics, tracer=self.tracer).start()
        # frontend encode pool (serve/frontend.py): cold-request encode on
        # supervised workers past the GIL; inline mode (the default) means
        # no pool at all. A process-mode vocab-hash mismatch raises out of
        # start() here — serve startup fails fast rather than scoring with
        # divergent vocabularies. An injected pool (the bench, scan) is
        # the caller's to stop.
        self._owns_frontend = frontend_pool is None
        if frontend_pool is not None:
            self.frontend = frontend_pool
        else:
            self.frontend = FrontendPool.from_config(
                vocabs, self.cfg.frontend, metrics=self.metrics,
                tracer=self.tracer, vocab_source=vocab_source)
            if self.frontend is not None:
                self.frontend.start()
        # admission control + QoS classes + brownout (serve/admission.py):
        # shed load BEFORE encode cost is paid — always a 429 with a
        # deterministic Retry-After, never a 5xx; under sustained SLO burn
        # the brownout controller steps through declared degradation
        # levels (invariant candidate 30)
        adm_cfg = self.cfg.admission
        self.admission = None
        self.brownout = None
        if adm_cfg.enabled:
            self.admission = AdmissionController(
                adm_cfg, metrics=self.metrics, journal=journal,
                flight=self.flight)
            if adm_cfg.brownout:
                self.brownout = BrownoutController(
                    adm_cfg, self._observe_fast_burn, metrics=self.metrics,
                    journal=journal, flight=self.flight).start()
                self.admission.brownout = self.brownout
        # continuous-learning capture (continual/capture.py): a sampled,
        # bounded journal of scored requests feeding shadow replay and
        # incremental retraining. Invariant 20 lives inside the capture —
        # record_request never raises — so the hook below is bare.
        cont_cfg = self.cfg.continual
        self.capture = None
        if cont_cfg.enabled and cont_cfg.capture_path:
            from deepdfa_tpu.continual.capture import TrafficCapture

            self.capture = TrafficCapture(
                Path(cont_cfg.capture_path),
                sample_every=cont_cfg.capture_sample_every,
                max_records=cont_cfg.capture_max_records,
                flight=self.flight)
        self._draining = threading.Event()
        self._stop_requested = threading.Event()
        self._stopped = threading.Event()
        self.httpd = ThreadingHTTPServer(
            (self.cfg.host, self.cfg.port), _make_handler(self))
        self.httpd.daemon_threads = True  # a hung socket must not block exit
        self._serve_thread: threading.Thread | None = None

    # -- lifecycle ----------------------------------------------------------

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    @property
    def draining(self) -> bool:
        # a requested-but-not-yet-started drain counts: from the instant
        # SIGTERM lands, /healthz must stop advertising this replica so the
        # LB routes elsewhere while in-flight work finishes
        return self._draining.is_set() or self._stop_requested.is_set()

    def warmup(self) -> dict:
        """Warm the engine's bucket ladder (through the warm store when
        one is wired), publish the report to /metrics, and return it."""
        report = self.engine.warmup(warm_store=self.warm_store,
                                    journal=self.journal)
        self.metrics.set_warmup(report)
        return report

    def start(self) -> "ScoreServer":
        if self.replica_id is None:
            self.replica_id = f"{self.cfg.host}:{self.port}"
        if self.tracer is not None:
            self.tracer.proc = f"serve:{self.replica_id}"
        self._serve_thread = threading.Thread(
            target=self.httpd.serve_forever, name="serve-http", daemon=True)
        self._serve_thread.start()
        logger.info("serving on %s:%s (%d buckets, max_batch=%d)",
                    self.cfg.host, self.port, len(self.engine.buckets),
                    self.cfg.max_batch)
        return self

    def install_signal_handlers(self) -> None:
        """SIGTERM/SIGINT → request a graceful drain. The handler only
        sets a flag; the actual drain runs in :meth:`wait` (signal
        handlers must not join threads). SIGUSR2 → dump the flight
        recorder (the live-incident probe)."""
        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, lambda *_: self._stop_requested.set())
        install_sigusr2(self.flight)

    def wait(self) -> dict:
        """Block until a shutdown is requested, then drain and stop.
        Returns the final metrics snapshot (also what ``main`` prints)."""
        while not self._stop_requested.wait(timeout=0.2):
            pass
        return self.shutdown(drain=True)

    def request_stop(self) -> None:
        self._stop_requested.set()

    def shutdown(self, drain: bool = True) -> dict:
        """Refuse new scores, drain queue + in-flight handlers, close."""
        self._draining.set()
        self._stop_requested.set()
        if self.brownout is not None:
            self.brownout.stop()
        if self.frontend is not None and self._owns_frontend:
            self.frontend.stop(drain=drain, timeout=self.cfg.drain_timeout_s)
        self.batcher.stop(drain=drain, timeout=self.cfg.drain_timeout_s)
        if self.cascade is not None:
            self.cascade.stop(drain=drain, timeout=self.cfg.drain_timeout_s)
        deadline = time.monotonic() + self.cfg.drain_timeout_s
        while drain and self.metrics.inflight > 0:
            if time.monotonic() >= deadline:
                logger.warning("drain timeout with %d request(s) in flight",
                               self.metrics.inflight)
                break
            time.sleep(0.01)
        self.httpd.shutdown()
        self.httpd.server_close()
        self._stopped.set()
        snap = self.metrics.snapshot()
        snap["cache"] = self.cache.stats()
        if self.admission is not None:
            snap["admission"] = self.admission.summary()
        if self.brownout is not None:
            snap["brownout"] = self.brownout.summary()
        return snap

    # -- verdict layer (/slo) ----------------------------------------------

    def _slo_snapshot(self) -> dict:
        """The flat snapshot the SLO specs read: response counters split
        by badness, the p99 gauge, and the drift sentinel's alert count
        (the PR 8 PSI alert, wired into action here).

        The latency gauges go ``None`` once no response has completed
        within the fast SLO window: the reservoir quantile is a memory of
        the LAST traffic, and a replica that reads as slow while serving
        nothing can never be sent traffic to prove otherwise — the
        federation's spillover demotion plus a frozen burn is a permanent
        saturation deadlock. No traffic in the window means no latency
        verdict, the same honesty rule the ratio burn already applies."""
        snap = self.metrics.snapshot()
        responses = snap.get("responses_total") or {}
        total = sum(responses.values())
        bad_5xx = sum(n for code, n in responses.items() if int(code) >= 500)
        errors = sum(n for code, n in responses.items() if int(code) >= 400)
        drift_alerting = sum(
            1 for row in self.drift.snapshot().values() if row["alert"])
        now = time.monotonic()
        if total != self._slo_traffic_mark[0]:
            self._slo_traffic_mark = (total, now)
        idle = (now - self._slo_traffic_mark[1]) >= self.slo.fast_window_s
        return {
            "responses_total": total,
            "responses_5xx_total": bad_5xx,
            "responses_error_total": errors,
            "latency_p99_ms": None if idle else snap.get("latency_p99_ms"),
            "drift_alerting": drift_alerting,
            # cascade keys — read by the tier-2 specs when enabled
            "tier2_latency_p99_ms": (None if idle
                                     else snap.get("tier2_latency_p99_ms")),
            "cascade_escalated_total": snap.get("cascade_escalated_total"),
            "cascade_degraded_total": snap.get("cascade_degraded_total"),
        }

    def _observe_slo(self) -> None:
        """One SLO evaluation against the live snapshot: journal any
        alert transitions as events and refresh the ``alerts.json``
        promotion veto. Both the ``/slo`` scrape and the brownout
        controller's poll drive this same path, so transitions are
        journaled identically no matter who observes first. None of the
        side effects can fail the caller (invariant 14 — drops count in
        ``obs_dropped_total``)."""
        events = self.slo.observe(self._slo_snapshot())
        if events:
            for evt in events:
                logger.warning("slo %s -> %s (burn fast=%s slow=%s)",
                               evt["slo"], evt["state"], evt["burn_fast"],
                               evt["burn_slow"])
                if self.journal is not None:
                    try:
                        self.journal.write(
                            event="slo_transition", slo=evt["slo"],
                            state=evt["state"], t_unix=evt["t_unix"],
                            burn_fast=evt["burn_fast"],
                            burn_slow=evt["burn_slow"])
                    except Exception:  # noqa: BLE001 — invariant 14
                        self.slo.dropped_total += 1
            if self.alerts_path is not None:
                if write_alerts_artifact(self.alerts_path,
                                         self.slo.statuses()) is None:
                    self.slo.dropped_total += 1

    def render_slo(self) -> str:
        """The ``/slo`` body, rendered through the shared registry
        (invariant 16) after one evaluation pass."""
        self._observe_slo()
        return self.slo.render("deepdfa_serve_")

    def _observe_fast_burn(self) -> float | None:
        """The brownout controller's signal source: drive one SLO
        evaluation (the exact path a ``/slo`` scrape drives) and return
        the worst fast-window burn across the specs."""
        self._observe_slo()
        return self.slo.worst_fast_burn()

    # -- request handling ---------------------------------------------------

    def _span(self, name: str, parent=None, root: bool = False, **attrs):
        """Tracer span when tracing is on, else a no-op context (yields
        None — callers must guard attribute writes)."""
        if self.tracer is None:
            return nullcontext()
        return self.tracer.span(name, parent=parent, root=root, **attrs)

    def handle_score(self, payload: dict) -> tuple[int, dict]:
        source = payload.get("source") if isinstance(payload, dict) else None
        if not isinstance(source, str) or not source.strip():
            return 400, {"error": "body must be JSON with a 'source' string"}
        # QoS tagging (serve/admission.py): every request carries a
        # priority class (default interactive — a human waiting on a
        # score) and a tenant for its token bucket
        qos = payload.get("class") or "interactive"
        if qos not in QOS_CLASSES:
            return 400, {"error": f"class must be one of "
                                  f"{'/'.join(QOS_CLASSES)}"}
        tenant = payload.get("tenant") or "default"
        if self.draining:
            return 503, {"error": "server is draining"}
        if faults.fire("serve.drop_request"):
            self.metrics.inc("dropped_total")
            self.flight.record("fault.fired", point="serve.drop_request")
            return 503, {"error": "request dropped (injected fault "
                                  "serve.drop_request)"}

        key = source_key(source)
        with self._span("cache.lookup") as sp:
            entry = self.cache.lookup(key)
            if sp is not None:
                sp.attrs["result_hit"] = bool(
                    entry is not None and entry.results is not None)
                sp.attrs["encode_hit"] = bool(
                    entry is not None and entry.results is None
                    and entry.encoded is not None)
        if entry is not None and entry.results is not None:
            # a result-level hit costs no encode or score work, so it is
            # served at EVERY brownout level without spending a token —
            # exactly the "warm-cache hits" half of brownout level 2
            return 200, {"results": entry.results, "cached": True}

        # admission control sits here — after the free cache hit, before
        # any encode cost is paid. A shed is a 429 with a deterministic
        # Retry-After (from bucket refill state), never a 5xx, and the
        # decision is already journaled + in the flight ring by the
        # controller (invariant 20)
        if self.admission is not None:
            decision = self.admission.admit(tenant, qos)
            if not decision["admit"]:
                return 429, {"error": "request shed by admission control",
                             "reason": decision["reason"],
                             "class": qos,
                             "retry_after_s": decision["retry_after_s"]}

        if entry is not None and entry.encoded is not None:
            encoded = entry.encoded  # frontend skipped: encode-level hit
        else:
            try:
                encoded = self._frontend_encode(source, key)
            except Exception as exc:  # noqa: BLE001 — frontend failure = 422
                return 422, {"error": f"{type(exc).__name__}: {exc}"}
            self.cache.store(key, encoded=encoded)
        if not encoded:
            return 422, {"error": "no functions found in source"}

        rows: list[dict] = []
        futures: list = []
        graphs: list = []  # aligned with rows; the tier-2 escalation payload
        for enc in encoded:
            if enc.graph is None:
                rows.append({"function": enc.name, "error": enc.error})
                futures.append(None)
                graphs.append(None)
                continue
            try:
                futures.append(self.batcher.submit(enc.graph))
            except QueueFullError as exc:
                self.metrics.inc("dropped_total")
                return 503, {"error": str(exc)}
            except OversizeGraphError as exc:
                return 413, {"error": str(exc)}
            except RuntimeError as exc:  # draining race
                return 503, {"error": str(exc)}
            rows.append({"function": enc.name})
            graphs.append(enc.graph)

        cascade = self.cascade
        tier1_rev = getattr(self.engine, "model_rev", None) or "unknown"
        t_req = time.monotonic()
        deadline = t_req + REQUEST_TIMEOUT_S
        # (row, tier-2 future, escalation time) — submitted as each tier-1
        # score lands, awaited together after the loop so escalations batch
        pending_t2: list[tuple[dict, object, float]] = []
        for row, fut, graph in zip(rows, futures, graphs):
            if fut is None:
                continue
            try:
                prob = fut.result(timeout=max(0.0, deadline - time.monotonic()))
            except (TimeoutError, _FutureTimeout):
                self.flight.record("request.timeout", function=row["function"])
                return 504, {"error": "scoring timed out"}
            except Exception as exc:  # noqa: BLE001 — engine fault = 500
                # the crash question "what was it doing?" gets a file:
                # record the failure, then dump the whole ring atomically
                self.flight.record("engine.error", function=row["function"],
                                   error=f"{type(exc).__name__}: {exc}")
                self.flight.dump("engine_error")
                return 500, {"error": f"{type(exc).__name__}: {exc}"}
            row["vulnerable_probability"] = round(prob, 6)
            if cascade is None:
                self.drift.observe(prob, tier1_rev)
                continue
            # cascade path: per-(model_rev, tier) drift keying + tier
            # attribution on every row; borderline scores escalate
            self.metrics.tier1_latency.observe(
                (time.monotonic() - t_req) * 1e3)
            self.drift.observe(prob, f"{tier1_rev}@t1")
            row["tier"] = 1
            row["tier1_score"] = round(prob, 6)
            if not cascade.in_band(prob):
                continue
            if (self.brownout is not None
                    and not cascade.escalation_allowed(self.brownout.level)):
                # brownout level >= 2 is tier-1 only: the tier-1 answer
                # is served, no tier-2 capacity is spent
                self.metrics.inc("brownout_suppressed_escalations_total")
                continue
            self.metrics.inc("cascade_escalated_total")
            with self._span("cascade.escalate", score=round(prob, 6),
                            band_lo=cascade.cfg.band_lo,
                            band_hi=cascade.cfg.band_hi):
                try:
                    fut2 = cascade.escalate(source, graph)
                except Exception as exc:  # noqa: BLE001 — invariant 24:
                    # enqueue failure (queue full, injected drop, draining)
                    # degrades to the tier-1 answer, never fails the request
                    self._cascade_degrade(row, exc)
                else:
                    pending_t2.append((row, fut2, time.monotonic()))

        for row, fut2, t_esc in pending_t2:
            remain = cascade.deadline_s - (time.monotonic() - t_esc)
            try:
                prob2 = fut2.result(timeout=max(0.0, remain))
            except Exception as exc:  # noqa: BLE001 — invariant 24: blown
                # deadline / tier-2 engine failure keeps the tier-1 answer
                self._cascade_degrade(row, exc)
                continue
            self.metrics.tier2_latency.observe(
                (time.monotonic() - t_esc) * 1e3)
            row["tier"] = 2
            row["vulnerable_probability"] = round(prob2, 6)
            self.drift.observe(prob2, f"{cascade.model_rev}@t2")
        if cascade is not None:
            for row, fut in zip(rows, futures):
                if fut is not None:
                    self.metrics.observe_answered(row["tier"])

        if self.capture is not None:
            # capture records the request as served (scores, tiers, the
            # encoded graphs) — and can never fail it (invariant 20)
            self.capture.record_request(key, rows, graphs,
                                        model_rev=tier1_rev)
        self.cache.store(key, results=rows)
        return 200, {"results": rows, "cached": False}

    def _frontend_encode(self, source: str, key: str):
        """Encode one cold source. With a pool: submit → await under the
        request deadline, so the encode runs on a supervised worker and
        overlaps the batcher's device dispatches. ANY pool-level failure
        — backpressure (``QueueFullError``), draining, pool death, a
        blown wait — **degrades to inline encode** (standing invariant
        25): pool trouble must never become a new 5xx and ``/healthz``
        stays green. Only :data:`~.frontend.ENCODE_ITEM_ERRORS` propagate
        — the item itself failed to encode, which is the caller's 422."""
        pool = self.frontend
        if pool is not None:
            try:
                fut = pool.submit(source, key=key)
            except Exception as exc:  # noqa: BLE001 — unavailability
                self._frontend_degrade(exc)
            else:
                try:
                    return fut.result(timeout=REQUEST_TIMEOUT_S)
                except ENCODE_ITEM_ERRORS:
                    raise
                except Exception as exc:  # noqa: BLE001 — pool trouble
                    self._frontend_degrade(exc)
        with self._span("frontend.encode", mode="inline"):
            return encode_source(source, self.vocabs, keep_cpg=False)

    def _frontend_degrade(self, exc: Exception) -> None:
        """Invariant 25: the request proceeds on inline encode; the
        degradation is counted and flight-recorded, never surfaced."""
        self.metrics.inc("frontend_inline_total")
        self.flight.record("frontend.degraded",
                           reason=f"{type(exc).__name__}: {exc}")

    def _cascade_degrade(self, row: dict, exc: Exception) -> None:
        """Invariant 24: tier-2 failure keeps the tier-1 answer. The row is
        marked, the degradation counted and journaled — never a 5xx."""
        self.metrics.inc("cascade_degraded_total")
        row["tier2_degraded"] = True
        self.flight.record("cascade.degraded", function=row.get("function"),
                           reason=f"{type(exc).__name__}: {exc}")


def _make_handler(server: ScoreServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # route BaseHTTPServer noise
            logger.debug("http: " + fmt, *args)

        def _send(self, code: int, body, content_type="application/json",
                  extra_headers=None):
            data = (body.encode() if isinstance(body, str)
                    else json.dumps(body).encode())
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            for name, value in (extra_headers or {}).items():
                self.send_header(name, value)
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            if self.path == "/healthz":
                # distinct draining state + 503 once SIGTERM is received:
                # LB health checks key on the status code, so the replica
                # drops out of rotation before the drain completes
                # the router's readiness gate keys on this body: replica
                # identity, the warm bucket ladder, and the content hashes
                # that decide whether a warm-store artifact is usable
                draining = server.draining
                eng = server.engine
                self._send(503 if draining else 200,
                           {"status": "draining" if draining else "ok",
                            "draining": draining,
                            "replica_id": server.replica_id,
                            "warm": bool(eng.warm_buckets),
                            "warm_buckets": list(eng.warm_buckets),
                            "vocab_hash": eng.vocab_hash,
                            "model_rev": eng.model_rev,
                            "precision": eng.precision,
                            "n_replicas": eng.n_replicas,
                            "label_style": eng.label_style,
                            "cascade": server.cascade is not None,
                            "tier2_model_rev": (
                                server.cascade.model_rev
                                if server.cascade is not None else None),
                            "frontend": (
                                {"mode": server.frontend.cfg.mode,
                                 "alive": server.frontend.alive}
                                if server.frontend is not None
                                else {"mode": "inline", "alive": True}),
                            # the overload-signal surface (ISSUE 18): the
                            # admission layer, autoscaler and federation
                            # router read these same two numbers, and the
                            # brownout level is reported honestly — a
                            # browned-out replica must say so
                            "frontend_queue_wait_p99_ms": (
                                server.metrics.frontend_queue_wait
                                .quantile(0.99)),
                            "admission": server.admission is not None,
                            "brownout_level": (
                                server.brownout.level
                                if server.brownout is not None else 0),
                            "brownout": (
                                server.brownout.level_name
                                if server.brownout is not None
                                else "normal")})
            elif self.path == "/metrics":
                self._send(200, server.metrics.render(server.cache.stats()),
                           content_type="text/plain; version=0.0.4")
            elif self.path == "/slo":
                self._send(200, server.render_slo(),
                           content_type="text/plain; version=0.0.4")
            else:
                self._send(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            if self.path != "/score":
                self._send(404, {"error": f"no route {self.path}"})
                return
            t0 = time.perf_counter()
            server.metrics.inc("requests_total")
            server.metrics.inc("inflight")
            try:
                try:
                    length = int(self.headers.get("Content-Length") or 0)
                    payload = json.loads(self.rfile.read(length) or b"{}")
                except (ValueError, json.JSONDecodeError):
                    code, body = 400, {"error": "body is not valid JSON"}
                else:
                    # the backend half of the trace: the router's
                    # traceparent (when forwarded) parents this root span,
                    # so one trace_id covers both processes
                    parent = (parse_traceparent(
                        self.headers.get("traceparent"))
                        if server.tracer is not None else None)
                    with server._span("server.request", parent=parent,
                                      root=True) as sp:
                        code, body = server.handle_score(payload)
                        if sp is not None:
                            sp.attrs["code"] = code
            except Exception as exc:  # noqa: BLE001 — request dies, server not
                code, body = 500, {"error": f"{type(exc).__name__}: {exc}"}
                server.flight.record("handler.crash",
                                     error=f"{type(exc).__name__}: {exc}")
                server.flight.dump("handler_crash")
            finally:
                server.metrics.inc("inflight", -1)
            headers = None
            if code == 429 and isinstance(body, dict) \
                    and "retry_after_s" in body:
                # the shed contract (invariant candidate 30): every 429
                # carries a Retry-After derived from bucket refill state
                headers = {"Retry-After": str(body["retry_after_s"])}
            self._send(code, body, extra_headers=headers)
            ms = (time.perf_counter() - t0) * 1000.0
            server.metrics.observe_response(code, ms)
            server.flight.record("request", code=code, ms=round(ms, 3))

    return Handler


# ---------------------------------------------------------------------------
# construction + CLI entry


def build_server(cfg: ExperimentConfig, run_dir: Path | None = None,
                 ckpt_dir: Path | None = None,
                 artifact: Path | str | None = None,
                 shard_dir: Path | str | None = None,
                 journal=None) -> ScoreServer:
    """Wire vocabs + engine + server from a config: either a checkpoint
    run (``run_dir``/``ckpt_dir``) or a pre-exported ``artifact`` dir.
    ``serve.warm_store_dir`` attaches the fleet warm-start store."""
    from deepdfa_tpu import utils

    if shard_dir is None:
        sample = "_sample" if cfg.data.sample else ""
        shard_dir = utils.processed_dir() / cfg.data.dsname / f"shards{sample}"
    vocabs = load_vocabs(shard_dir)
    if artifact is not None:
        engine = ScoringEngine.from_artifact(artifact, vocabs=vocabs)
    else:
        if run_dir is None and ckpt_dir is None:
            raise ValueError("need --run-dir/--ckpt-dir or --artifact")
        engine = ScoringEngine.from_checkpoint(
            cfg, ckpt_dir or Path(run_dir) / "checkpoints", vocabs,
            max_batch=cfg.serve.max_batch, journal=journal)
    warm_store = None
    if cfg.serve.warm_store_dir:
        from .warmstore import WarmStore

        warm_store = WarmStore(cfg.serve.warm_store_dir)
    return ScoreServer(engine, vocabs, cfg.serve, warm_store=warm_store,
                       journal=journal, vocab_source=shard_dir)


def serve_command(cfg: ExperimentConfig, run_dir: Path | None = None,
                  ckpt_dir: Path | None = None,
                  artifact: Path | str | None = None,
                  shard_dir: Path | str | None = None,
                  journal=None) -> dict:
    """Foreground service: build, warm, serve until SIGTERM, drain."""
    server = build_server(cfg, run_dir=run_dir, ckpt_dir=ckpt_dir,
                          artifact=artifact, shard_dir=shard_dir,
                          journal=journal)
    warmed = server.warmup()
    server.install_signal_handlers()
    server.start()
    print(json.dumps({
        "status": "serving", "host": server.cfg.host, "port": server.port,
        "replica_id": server.replica_id,
        "buckets_warmed": warmed["buckets"],
        "warm_store": {k: warmed[k] for k in
                       ("hits", "misses", "compile_seconds_saved")},
        "label_style": server.engine.label_style,
        "vocab_hash": server.engine.vocab_hash,
        "model_rev": server.engine.model_rev,
        "cascade": ({"band": [cfg.serve.cascade.band_lo,
                              cfg.serve.cascade.band_hi],
                     "tier2_model_rev": server.cascade.model_rev}
                    if server.cascade is not None else None),
    }), flush=True)
    summary = server.wait()
    print(json.dumps({"status": "drained", **{
        k: summary[k] for k in ("requests_total", "batches_total",
                                "mean_batch_occupancy") if k in summary}}),
        flush=True)
    return summary


def main(argv=None) -> dict:
    import argparse

    from deepdfa_tpu.config import load_config

    parser = argparse.ArgumentParser(prog="deepdfa-tpu-serve")
    parser.add_argument("--config", action="append", default=[])
    parser.add_argument("--set", action="append", default=[], dest="overrides",
                        help="dotted overrides, e.g. --set serve.max_batch=32")
    parser.add_argument("--run-dir", default=None)
    parser.add_argument("--ckpt-dir", default=None)
    parser.add_argument("--artifact", default=None,
                        help="pre-exported StableHLO artifact dir "
                             "(deepdfa-tpu export) instead of a checkpoint")
    parser.add_argument("--shard-dir", default=None,
                        help="shard dir holding vocab.json (default: the "
                             "config's processed dataset dir)")
    parser.add_argument("--journal", default=None,
                        help="journal file for warmup / int8-gate events")
    args = parser.parse_args(argv)

    layers = list(args.config)
    if args.run_dir and (Path(args.run_dir) / "config.json").exists():
        layers.insert(0, Path(args.run_dir) / "config.json")

    def _parse(pairs):
        out = {}
        for pair in pairs:
            key, _, value = pair.partition("=")
            try:
                out[key] = json.loads(value)
            except json.JSONDecodeError:
                out[key] = value
        return out

    cfg = load_config(*layers, overrides=_parse(args.overrides))
    logging.basicConfig(level=logging.INFO)
    journal = None
    if args.journal:
        from deepdfa_tpu.resilience.journal import RunJournal

        journal = RunJournal(Path(args.journal))
    return serve_command(
        cfg, run_dir=Path(args.run_dir) if args.run_dir else None,
        ckpt_dir=Path(args.ckpt_dir) if args.ckpt_dir else None,
        artifact=args.artifact, shard_dir=args.shard_dir, journal=journal)


if __name__ == "__main__":
    main()
