"""Content-addressed scan cache: normalized source → encoded graphs + scores.

The dominant cost of a scan is everything BEFORE the model — parsing,
dependence edges, feature extraction, vocab encoding (the "frontend").
Keying on the content address of the *normalized* source text
(:func:`deepdfa_tpu.pipeline.source_key`) means a repeated scan of the
same function skips all of it; whitespace-only edits share the entry.

Entries hold two layers that fill independently:

- ``encoded`` — the :class:`~deepdfa_tpu.pipeline.EncodedFunction` list,
  written as soon as the frontend succeeds;
- ``results`` — the final per-function score rows, written only after the
  engine scored them.

A request that raced a fault (``serve.engine_raises``) leaves ``encoded``
behind, so its retry skips the frontend and only re-scores — hence two
hit counters (``hits`` = full result hit, ``encode_hits`` = frontend
skipped but scoring re-ran). Eviction is plain LRU under one lock;
``capacity=0`` disables caching entirely (every lookup is a miss).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass

__all__ = ["ScanEntry", "ScanCache"]


@dataclass
class ScanEntry:
    encoded: list | None = None
    results: list | None = None


@dataclass
class _Stats:
    hits: int = 0
    encode_hits: int = 0
    misses: int = 0
    evictions: int = 0


class ScanCache:
    """Thread-safe LRU over ``source_key(code)`` → :class:`ScanEntry`."""

    def __init__(self, capacity: int = 4096):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._entries: OrderedDict[str, ScanEntry] = OrderedDict()
        self._stats = _Stats()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def lookup(self, key: str) -> ScanEntry | None:
        """Get-and-touch. Counts one hit (full or encode-level) or miss."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None or self.capacity == 0:
                self._stats.misses += 1
                return None
            self._entries.move_to_end(key)
            if entry.results is not None:
                self._stats.hits += 1
            elif entry.encoded is not None:
                self._stats.encode_hits += 1
            else:  # placeholder left by a failed fill — treat as a miss
                self._stats.misses += 1
                return None
            return entry

    def store(self, key: str, *, encoded=None, results=None) -> None:
        """Create or deepen the entry for ``key`` (does not count a hit)."""
        if self.capacity == 0:
            return
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                entry = ScanEntry()
                self._entries[key] = entry
            if encoded is not None:
                entry.encoded = encoded
            if results is not None:
                entry.results = results
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                self._stats.evictions += 1

    def stats(self) -> dict:
        """Counters + derived hit rate (full hits ÷ lookups)."""
        with self._lock:
            s = self._stats
            lookups = s.hits + s.encode_hits + s.misses
            return {
                "hits": s.hits,
                "encode_hits": s.encode_hits,
                "misses": s.misses,
                "evictions": s.evictions,
                "entries": len(self._entries),
                "hit_rate": (s.hits / lookups) if lookups else 0.0,
            }
