"""Online inference service: dynamic micro-batching over warm compiled
scorers, a content-addressed scan cache, and a stdlib HTTP endpoint with
Prometheus-style serving metrics.

Composition (one request's path)::

    POST /score ──(drop/backpressure faults, cache lookup)──▶ pipeline
        encode_source ──▶ MicroBatcher.submit ──▶ size bucket queue
        ──(max_batch | max_wait_ms)──▶ ScoringEngine.score (padded,
        per-bucket compiled callable) ──▶ futures resolve ──▶ JSON rows

Entry points: ``python -m deepdfa_tpu.serve.server`` or
``deepdfa-tpu serve``; load-test with ``scripts/bench_serving.py``.
"""

from .batcher import MicroBatcher, QueueFullError
from .cache import ScanCache, ScanEntry
from .engine import OversizeGraphError, ScoringEngine, ServeBucket, serve_buckets
from .metrics import LatencyReservoir, ServeMetrics
from .server import ScoreServer, build_server, serve_command

__all__ = [
    "MicroBatcher",
    "QueueFullError",
    "ScanCache",
    "ScanEntry",
    "OversizeGraphError",
    "ScoringEngine",
    "ServeBucket",
    "serve_buckets",
    "LatencyReservoir",
    "ServeMetrics",
    "ScoreServer",
    "build_server",
    "serve_command",
]
