"""Online inference service: dynamic micro-batching over warm compiled
scorers, a content-addressed scan cache, and a stdlib HTTP endpoint with
Prometheus-style serving metrics.

Composition (one request's path)::

    POST /score ──(drop/backpressure faults, cache lookup)──▶ pipeline
        encode_source ──▶ MicroBatcher.submit ──▶ size bucket queue
        ──(max_batch | max_wait_ms)──▶ ScoringEngine.score (padded,
        per-bucket compiled callable) ──▶ futures resolve ──▶ JSON rows

Fleet mode (many replicas, one cache): ``serve/router.py`` fronts N
ScoreServers, consistent-hashing ``source_key`` so the scan cache shards
shared-nothing; ``serve/warmstore.py`` hands joining replicas their
compiled bucket ladder (zero cold compiles); ``serve/autoscaler.py``
closes the loop — an SLO-driven supervisor that spawns, drains, and
replaces replicas through the same warm-join/drain protocol; ``mesh=``
engines replicate scoring across local devices in one process.

Entry points: ``python -m deepdfa_tpu.serve.server`` (one replica),
``python -m deepdfa_tpu.serve.router`` (the fleet front); load-test with
``scripts/bench_serving.py`` (``--fleet N`` drives the whole topology).
"""

from .autoscaler import (
    AdminRouterClient,
    Autoscaler,
    SpawnError,
    SubprocessLauncher,
    SubprocessReplica,
)
from .batcher import MicroBatcher, QueueFullError
from .cache import ScanCache, ScanEntry
from .engine import (
    OversizeGraphError,
    PendingScore,
    ScoringEngine,
    ServeBucket,
    serve_buckets,
)
from .frontend import (
    ENCODE_ITEM_ERRORS,
    FrontendPool,
    FrontendProcessSession,
    ThreadEncodeSession,
    VocabHashMismatch,
    encode_session_factory,
)
from .federation import Cell, FederationMetrics, FederationRouter
from .metrics import LatencyReservoir, ServeMetrics
from .router import Backend, FleetRouter, HashRing, RouterMetrics
from .server import ScoreServer, build_server, serve_command
from .warmstore import WarmEntry, WarmStore, bucket_artifact_key

__all__ = [
    "AdminRouterClient",
    "Autoscaler",
    "SpawnError",
    "SubprocessLauncher",
    "SubprocessReplica",
    "MicroBatcher",
    "QueueFullError",
    "ScanCache",
    "ScanEntry",
    "OversizeGraphError",
    "PendingScore",
    "ScoringEngine",
    "ServeBucket",
    "serve_buckets",
    "ENCODE_ITEM_ERRORS",
    "FrontendPool",
    "FrontendProcessSession",
    "ThreadEncodeSession",
    "VocabHashMismatch",
    "encode_session_factory",
    "LatencyReservoir",
    "ServeMetrics",
    "Backend",
    "Cell",
    "FederationMetrics",
    "FederationRouter",
    "FleetRouter",
    "HashRing",
    "RouterMetrics",
    "ScoreServer",
    "build_server",
    "serve_command",
    "WarmEntry",
    "WarmStore",
    "bucket_artifact_key",
]
