"""Content-addressed function-embedding cache for the hierarchical scorer.

The level-1 half of ``models/ggnn_hier.py`` — the fused/megabatch
per-function GGNN — is by far the expensive part of whole-program scoring,
yet a repo re-scan touches a handful of functions. This cache makes a warm
rescan pay ZERO level-1 dispatches: entries are keyed on
:func:`deepdfa_tpu.pipeline.source_key` of the function's source (the same
whitespace-normalized sha256 the scan/extract caches use) salted with the
full pipeline generation — ``model_rev`` (the parameter content hash),
the vocabulary content hash, and the feature configuration — so a new
checkpoint, a re-vocabed corpus, or a feature-family flip each MISS
cleanly instead of serving embeddings from a different model (the
invariant-23 generation-salt pattern).

Commit protocol (ROADMAP invariants 1/10/23): the raw float32 payload
lands FIRST via ``atomic_write_bytes``, then the ``{key}.json`` meta
marker commits the entry via ``atomic_write_text``. An entry exists iff
its meta exists; a torn write, a missing payload, a meta/payload digest
mismatch or a wrong-width blob all read as a MISS — never as a decode
crash (the ``embcache.cache_corrupt`` chaos point pins it). Writers race
benignly: identical content under content-addressed names, last
``os.replace`` wins.
"""

from __future__ import annotations

import hashlib
import json
import threading
from dataclasses import dataclass
from pathlib import Path

import numpy as np

from deepdfa_tpu.resilience import faults
from deepdfa_tpu.resilience.journal import atomic_write_bytes, atomic_write_text

__all__ = ["EMBCACHE_VERSION", "FunctionEmbeddingCache"]

# Bump when the level-1 embedding's OUTPUT changes shape/content for the
# same (source, model_rev, vocab, features) — old entries then miss
# instead of resurrecting embeddings from a different encoder.
EMBCACHE_VERSION = 1


@dataclass
class _Stats:
    hits: int = 0
    misses: int = 0
    corrupt: int = 0
    puts: int = 0


class FunctionEmbeddingCache:
    """``key(code) -> get/put`` of ``[dim]`` float32 pooled embeddings."""

    def __init__(self, root: str | Path, *, model_rev: str, vocab_hash: str,
                 feature_salt: str = "", dim: int | None = None,
                 version: int = EMBCACHE_VERSION):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.dim = dim
        # the generation salt: model revision × vocabulary × feature
        # config, folded into every key so entries from any other serving
        # identity cannot collide (invariant 23)
        self._salt = hashlib.sha256(
            f"embcache-v{int(version)}:{model_rev}:{vocab_hash}:"
            f"{feature_salt}".encode()).hexdigest()[:16]
        self._lock = threading.Lock()
        self._stats = _Stats()

    # -- keys ---------------------------------------------------------------
    def key(self, code: str) -> str:
        """Content address of one function's source under this cache's
        serving generation (``source_key`` ⊕ model/vocab/feature salt)."""
        from deepdfa_tpu.pipeline import source_key

        return hashlib.sha256(
            f"{source_key(code)}:{self._salt}".encode()).hexdigest()

    def _paths(self, key: str) -> tuple[Path, Path]:
        return self.root / f"{key}.f32", self.root / f"{key}.json"

    # -- protocol -----------------------------------------------------------
    def get(self, key: str) -> np.ndarray | None:
        """The committed embedding for ``key``, or None (MISS). Any torn,
        corrupt or injected-corrupt entry is a MISS, never an exception."""
        payload_path, meta_path = self._paths(key)
        try:
            meta = json.loads(meta_path.read_text())
            blob = payload_path.read_bytes()
            if faults.fire("embcache.cache_corrupt"):
                blob = blob[: len(blob) // 2] + b"\x00corrupt"
            if meta.get("sha256") != hashlib.sha256(blob).hexdigest():
                raise ValueError("payload digest mismatch")
            emb = np.frombuffer(blob, np.float32)
            if emb.size != int(meta.get("dim", -1)):
                raise ValueError("payload width mismatch")
            if self.dim is not None and emb.size != self.dim:
                raise ValueError("embedding width != this scorer's out_dim")
        except FileNotFoundError:
            with self._lock:
                self._stats.misses += 1
            return None
        except Exception:  # noqa: BLE001 — corrupt entry == miss, by design
            with self._lock:
                self._stats.misses += 1
                self._stats.corrupt += 1
            return None
        with self._lock:
            self._stats.hits += 1
        return emb.copy()

    def put(self, key: str, emb: np.ndarray) -> None:
        """Commit payload-first: the ``{key}.json`` meta marker is written
        only after the float32 payload is durably in place."""
        arr = np.ascontiguousarray(np.asarray(emb, np.float32).reshape(-1))
        payload_path, meta_path = self._paths(key)
        blob = arr.tobytes()
        atomic_write_bytes(payload_path, blob)
        atomic_write_text(meta_path, json.dumps({
            "schema": 1,
            "sha256": hashlib.sha256(blob).hexdigest(),
            "bytes": len(blob),
            "dim": int(arr.size),
        }))
        with self._lock:
            self._stats.puts += 1

    # -- accounting ---------------------------------------------------------
    def __len__(self) -> int:
        return sum(1 for _ in self.root.glob("*.json"))

    def stats(self) -> dict:
        with self._lock:
            s = self._stats
            lookups = s.hits + s.misses
            return {
                "hits": s.hits,
                "misses": s.misses,
                "corrupt": s.corrupt,
                "puts": s.puts,
                "hit_rate": (s.hits / lookups) if lookups else 0.0,
            }
