"""Hierarchical two-level GGNN: whole-program scoring that never falls
off the fused kernels.

A merged file/repo CPG blows past the largest VMEM-admittable serving
bucket (4094 nodes), so whole-unit scoring cannot ride the per-function
ladder — and routing a merged graph to the megabatch segment twin would
abandon the fused-kernel MFU story the packer bought. The standard answer
(the "GNN Acceleration" survey's hierarchical composition + subgraph
reuse) maps cleanly onto DeepDFA's per-function embedding:

- **Level 1** — the existing fused/megabatch per-function GGNN, stopped
  at the pooled embedding: :func:`~deepdfa_tpu.ops.megabatch.
  fused_ggnn_encoder` is the SAME whole-model kernel (same param tree,
  same prologue/rounds/pooling epilogue) with the head matmuls elided,
  fed by this module's own first-fit-decreasing megabatch packer. Per-
  function embeddings are bit-identical to the standalone fused path —
  the packer and cache plumbing never perturb a bit (pinned in
  ``tests/test_hier.py``). Shapes the VMEM plan refuses route to
  :func:`~deepdfa_tpu.ops.megabatch.megabatch_encoder_reference` and are
  counted in ``n_fallback_dispatches`` — the bench gate holds that count
  at zero on every fixture unit.
- **Embedding cache** — a content-addressed
  :class:`~deepdfa_tpu.serve.embcache.FunctionEmbeddingCache` in front of
  level 1 (key = normalized function source × model_rev × vocab hash ×
  feature config), so a repo re-scan re-embeds only cache-missed
  functions and a warm rescan does ZERO level-1 dispatches.
- **Level 2** — :class:`CallGraphGGNN`, a small GGNN over the call graph:
  one node per function (its level-1 embedding concatenated with
  ``_DFA_ireach``/``_DFA_itaint`` interprocedural summaries), edges from
  :mod:`deepdfa_tpu.cpg.callgraph` (made bidirectional: taint travels
  caller→callee through params and callee→caller through returns),
  producing the unit-level score plus the per-function attribution
  readout that lands in ``scan.json``.

Level-2 parameters are derived deterministically from the level-1
``model_rev`` (the parameter content hash) — same checkpoint, same unit
scores, across processes and sessions.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import numpy as np

from deepdfa_tpu.config import ALL_SUBKEYS, GGNNConfig
from deepdfa_tpu.data.graphs import Graph, batch_np

__all__ = [
    "UnitFunction",
    "CallGraphGGNN",
    "HierScorer",
    "megabatch_compatible",
    "unit_call_edges",
    "unit_summaries",
    "N_SUMMARY_FEATURES",
]

# per-function interprocedural summary width fed to level 2 alongside the
# level-1 embedding: [log1p(n_nodes), log1p(Σ ireach), clip(max ireach)/8,
# max itaint / 3, any cross-boundary-only taint, log1p(callers),
# log1p(callees)]
N_SUMMARY_FEATURES = 7


def megabatch_compatible(cfg: GGNNConfig) -> bool:
    """Whether ``cfg`` is servable by the whole-model fused kernel — the
    same constraints :class:`~deepdfa_tpu.models.ggnn_megabatch.
    GGNNMegabatch` enforces at setup. Engines outside this envelope have
    no hierarchical path (``score_unit`` raises)."""
    return (cfg.concat_all_absdf
            and not cfg.dataflow_families
            and not cfg.interproc_families
            and cfg.label_style == "graph"
            and not cfg.encoder_mode
            and cfg.aggregation == "sum")


@dataclasses.dataclass(frozen=True)
class UnitFunction:
    """One function of a scoring unit: the name the call graph resolves,
    the source text the embedding cache keys on, and the encoded graph
    level 1 embeds on a miss."""

    name: str
    code: str
    graph: Graph


# ---------------------------------------------------------------------------
# level 2: the call-graph GGNN


def _build_level2(hidden: int, n_steps: int):
    import flax.linen as nn
    import jax.numpy as jnp

    from deepdfa_tpu.models.ggnn import GRUCell

    class CallGraphGGNN(nn.Module):
        """Small GGNN over the call graph (one node per function).

        in_proj compresses ``concat([level-1 embedding, summaries])`` to
        the hidden width, ``n_steps`` message rounds run over the
        bidirectional call edges (Dense message + segment-sum + GRU — the
        level-1 update rule at call-graph scale), and the readout mirrors
        ``GlobalAttentionPooling``: a masked softmax gate pools the unit
        embedding for the unit head, while a per-node head emits the
        per-function attribution logits. Units are a handful of nodes, so
        this runs as plain XLA — no bucket ladder, no VMEM plan.
        """

        hidden: int
        n_steps: int

        @nn.compact
        def __call__(self, emb, senders, receivers, mask):
            import jax

            n = emb.shape[0]
            h = jnp.tanh(nn.Dense(self.hidden, name="in_proj")(emb))
            h0 = h
            edge = nn.Dense(self.hidden, name="edge_linear")
            gru = GRUCell(self.hidden, name="gru")
            for _ in range(self.n_steps):
                msg = edge(h)
                agg = jax.ops.segment_sum(
                    msg[senders], receivers, num_segments=n)
                h = gru(agg, h)
            hcat = jnp.concatenate([h, h0], axis=-1)
            gate_logit = nn.Dense(1, name="gate")(hcat)[:, 0]
            gate_logit = jnp.where(mask, gate_logit, -jnp.inf)
            gate = jax.nn.softmax(gate_logit)
            pooled = jnp.sum(gate[:, None] * hcat, axis=0)
            unit_logit = nn.Dense(1, name="out")(pooled)[0]
            fn_logit = nn.Dense(1, name="attr")(hcat)[:, 0]
            return unit_logit, fn_logit, gate

    return CallGraphGGNN(hidden=hidden, n_steps=n_steps)


def CallGraphGGNN(hidden: int = 32, n_steps: int = 2):
    """Construct the level-2 flax module (factory so flax stays a deferred
    import — see :func:`_build_level2` for the architecture)."""
    return _build_level2(hidden, n_steps)


# ---------------------------------------------------------------------------
# supergraph → level-2 inputs


def unit_call_edges(sg, names: Sequence[str]) -> tuple[np.ndarray, np.ndarray]:
    """Call-graph edges of ``sg`` mapped onto unit-function indices,
    bidirectional (taint flows both ways across a call boundary) with one
    self-loop per function so isolated functions still see their own
    state. Edges touching a method outside ``names`` are dropped."""
    index = {name: i for i, name in enumerate(names)}
    pairs: set[tuple[int, int]] = {(i, i) for i in range(len(names))}
    for caller_mid, callee_mid in sg.callgraph.edges:
        a = index.get(sg.method_names.get(caller_mid, ""))
        b = index.get(sg.method_names.get(callee_mid, ""))
        if a is None or b is None:
            continue
        pairs.add((a, b))
        pairs.add((b, a))
    ordered = sorted(pairs)
    senders = np.asarray([a for a, _ in ordered], np.int32)
    receivers = np.asarray([b for _, b in ordered], np.int32)
    return senders, receivers


def unit_summaries(sg, names: Sequence[str]) -> np.ndarray:
    """``[len(names), N_SUMMARY_FEATURES]`` per-function interprocedural
    summaries — the ``_DFA_ireach``/``_DFA_itaint`` node features of
    :func:`~deepdfa_tpu.cpg.interproc.interproc_node_features` folded to
    one row per function, computed on the supergraph the caller already
    built (no re-parse, no re-supergraph)."""
    from deepdfa_tpu.cpg.interproc import interproc_node_features

    feats = interproc_node_features(sg.base, sg=sg)
    mid_of = {name: mid for mid, name in sg.method_names.items()}
    by_owner: dict[int, list[int]] = {}
    for nid in sg.base.nodes:
        mid = sg.owner.get(nid)
        if mid is not None:
            by_owner.setdefault(mid, []).append(nid)
    callers: dict[int, int] = {}
    callees: dict[int, int] = {}
    for a, b in sg.callgraph.edges:
        callees[a] = callees.get(a, 0) + 1
        callers[b] = callers.get(b, 0) + 1
    out = np.zeros((len(names), N_SUMMARY_FEATURES), np.float32)
    for i, name in enumerate(names):
        mid = mid_of.get(name)
        if mid is None:
            continue
        nodes = by_owner.get(mid, [])
        ireach = [feats["ireach"].get(n, 0) for n in nodes]
        itaint = [feats["itaint"].get(n, 0) for n in nodes]
        out[i] = [
            math.log1p(len(nodes)),
            math.log1p(float(sum(ireach))),
            min(max(ireach, default=0), 8) / 8.0,
            max(itaint, default=0) / 3.0,
            1.0 if any(c >= 3 for c in itaint) else 0.0,
            math.log1p(float(callers.get(mid, 0))),
            math.log1p(float(callees.get(mid, 0))),
        ]
    return out


# ---------------------------------------------------------------------------
# the scorer


class HierScorer:
    """Two-level whole-unit scorer over a level-1 GGNN parameter tree.

    ``params`` is the (f32) parameter tree every layout shares
    (``embed_{sk}``/``ggnn``/``pooling`` — the head is never read);
    ``cfg``/``input_dim`` must be megabatch-compatible. ``cache`` (a
    :class:`~deepdfa_tpu.serve.embcache.FunctionEmbeddingCache`) is
    consulted before any level-1 work and written after; attach or swap
    it freely — it only ever stores finished embeddings.

    Counters (the bench gates read them): ``n_level1_dispatches`` fused-
    kernel launches, ``n_fallback_dispatches`` segment-twin launches
    (plan-refused shapes — held at zero on fixture units),
    ``level1_recompute`` functions embedded rather than served from
    cache.
    """

    #: level-1 megabatch admission budget per packed bin (graphs, nodes,
    #: edges) — far under the VMEM plan for the flagship config; the plan
    #: itself is still checked per bin and is what routing obeys
    MAX_BIN_GRAPHS = 64
    MAX_BIN_NODES = 4094

    def __init__(self, cfg: GGNNConfig, input_dim: int, params, *,
                 cache=None, model_rev: str | None = None,
                 level2_hidden: int = 32, level2_steps: int = 2):
        if not megabatch_compatible(cfg):
            raise ValueError(
                "HierScorer needs a megabatch-compatible level-1 config "
                "(concat_all_absdf=True, graph labels, sum aggregation, no "
                "dataflow/interproc families, no encoder_mode) — the whole "
                "point is that level 1 never leaves the fused kernels")
        import jax.numpy as jnp

        self.cfg = cfg
        self.input_dim = int(input_dim)
        self.cache = cache
        self.n_level1_dispatches = 0
        self.n_fallback_dispatches = 0
        self.level1_recompute = 0
        self.out_dim = 2 * cfg.hidden_dim * len(ALL_SUBKEYS)
        self._width = cfg.hidden_dim * len(ALL_SUBKEYS)

        p = params
        f32 = lambda a: jnp.asarray(a, jnp.float32)
        self._table = jnp.concatenate(
            [f32(p[f"embed_{sk}"]["embedding"]) for sk in ALL_SUBKEYS], axis=0)
        conv = p["ggnn"]
        self._ew, self._eb = (f32(conv["edge_linear"]["kernel"]),
                              f32(conv["edge_linear"]["bias"]))
        self._xw, self._xb = (f32(conv["gru"]["x_proj"]["kernel"]),
                              f32(conv["gru"]["x_proj"]["bias"]))
        self._hw, self._hb = (f32(conv["gru"]["h_proj"]["kernel"]),
                              f32(conv["gru"]["h_proj"]["bias"]))
        self._gw, self._gb = (f32(p["pooling"]["gate"]["kernel"]),
                              f32(p["pooling"]["gate"]["bias"]))
        if model_rev is None:
            from deepdfa_tpu.serve.engine import _params_content_hash

            model_rev = _params_content_hash(params)
        self.model_rev = model_rev
        self._level2 = _build_level2(level2_hidden, level2_steps)
        self._l2_params = self._init_level2()

    # -- level 2 init --------------------------------------------------------

    def _init_level2(self):
        """Level-2 params seeded from the level-1 model_rev: the derived
        head is a deterministic function of the checkpoint it extends.
        Hashing (rather than parsing) the revision keeps any string —
        content hash, artifact tag, test stub — a valid seed source."""
        import hashlib

        import jax
        import jax.numpy as jnp

        seed = int.from_bytes(
            hashlib.sha256(self.model_rev.encode()).digest()[:4], "big")
        emb = jnp.zeros((2, self.out_dim + N_SUMMARY_FEATURES), jnp.float32)
        snd = jnp.asarray([0, 1], jnp.int32)
        rcv = jnp.asarray([0, 1], jnp.int32)
        mask = jnp.ones(2, bool)
        return self._level2.init(
            jax.random.key(seed), emb, snd, rcv, mask)["params"]

    # -- level 1: pack + embed ----------------------------------------------

    def _plan(self, n_graphs: int, n_nodes: int, n_edges: int):
        from deepdfa_tpu.ops.megabatch import MegabatchPlan, _round_up

        return MegabatchPlan(
            max_graphs=n_graphs + 1,
            max_nodes=_round_up(max(n_nodes + 1, 8), 8),
            max_edges=_round_up(max(n_edges, 1), 128),
            width=self._width,
            n_steps=self.cfg.n_steps,
            table_rows=self.input_dim * len(ALL_SUBKEYS),
            embed_width=self.cfg.hidden_dim,
            n_head_layers=0,
        )

    def _pack(self, graphs: Sequence[Graph]) -> list[tuple[list[int], object]]:
        """First-fit-decreasing pack ``graphs`` into megabatch bins, each
        admitted by the padded VMEM plan; returns ``(indices, plan)`` per
        bin. Unlike :func:`~deepdfa_tpu.ops.megabatch.pack_megabatches`
        (which drops graph identity) every bin remembers which input
        graphs it carries — the embeddings must land back in order."""
        order = sorted(range(len(graphs)),
                       key=lambda i: (-graphs[i].n_nodes,
                                      -graphs[i].n_edges, i))
        bins: list[list[int]] = []
        loads: list[list[int]] = []  # [node-sum, edge-sum]
        for i in order:
            g = graphs[i]
            for b, load in zip(bins, loads):
                if len(b) >= self.MAX_BIN_GRAPHS:
                    continue
                nn_, ne_ = load[0] + g.n_nodes, load[1] + g.n_edges
                if nn_ > self.MAX_BIN_NODES:
                    continue
                if self._plan(len(b) + 1, nn_, ne_).fits:
                    b.append(i)
                    load[0], load[1] = nn_, ne_
                    break
            else:
                bins.append([i])
                loads.append([g.n_nodes, g.n_edges])
        return [(b, self._plan(len(b), load[0], load[1]))
                for b, load in zip(bins, loads)]

    def _embed_batch(self, batch) -> np.ndarray:
        """One packed batch → pooled embeddings ``[max_graphs, out_dim]``
        through the fused encoder, or the bit-identical segment twin when
        the plan refuses the realized shape."""
        import jax
        import jax.numpy as jnp

        from deepdfa_tpu.ops.megabatch import (
            fused_ggnn_encoder,
            megabatch_encoder_reference,
        )

        ids = jnp.stack(
            [jnp.asarray(batch.node_feats[f"_ABS_DATAFLOW_{sk}"])
             + i * self.input_dim
             for i, sk in enumerate(ALL_SUBKEYS)], axis=-1)
        plan = self._plan(batch.max_graphs - 1, batch.max_nodes - 1,
                          batch.senders.shape[0])
        args = (self._table, ids, jnp.asarray(batch.senders),
                jnp.asarray(batch.receivers), jnp.asarray(batch.node_gidx),
                jnp.asarray(batch.node_mask), self._ew, self._eb,
                self._xw, self._xb, self._hw, self._hb, self._gw, self._gb)
        if plan.fits:
            self.n_level1_dispatches += 1
            out = fused_ggnn_encoder(
                *args, n_steps=self.cfg.n_steps, n_graphs=batch.max_graphs,
                interpret=jax.default_backend() != "tpu", edges_sorted=True)
        else:
            self.n_fallback_dispatches += 1
            out = megabatch_encoder_reference(
                *args, n_steps=self.cfg.n_steps, n_graphs=batch.max_graphs,
                edges_sorted=True)
        return np.asarray(out, np.float32)

    def embed_graphs(self, graphs: Sequence[Graph]) -> np.ndarray:
        """Embed ``graphs`` through the megabatch packer + fused encoder —
        the standalone level-1 path (no cache): ``[len(graphs), out_dim]``
        in input order. This is the bit-identity baseline the hier tests
        pin :meth:`embed_functions` against."""
        out = np.zeros((len(graphs), self.out_dim), np.float32)
        for indices, plan in self._pack(graphs):
            batch = batch_np([graphs[i] for i in indices], plan.max_graphs,
                             plan.max_nodes, plan.max_edges)
            embs = self._embed_batch(batch)
            for slot, i in enumerate(indices):
                out[i] = embs[slot]
        return out

    def embed_functions(self, fns: Sequence[UnitFunction]) -> np.ndarray:
        """Cache-fronted level 1: consult the embedding cache per function,
        pack + embed only the misses, commit them back. A warm cache makes
        this ZERO dispatches (the bench's warm-rescan gate)."""
        out = np.zeros((len(fns), self.out_dim), np.float32)
        misses: list[tuple[int, str | None]] = []
        for i, fn in enumerate(fns):
            if self.cache is not None:
                key = self.cache.key(fn.code)
                hit = self.cache.get(key)
                if hit is not None and hit.size == self.out_dim:
                    out[i] = hit
                    continue
                misses.append((i, key))
            else:
                misses.append((i, None))
        if misses:
            embs = self.embed_graphs([fns[i].graph for i, _ in misses])
            self.level1_recompute += len(misses)
            for (i, key), e in zip(misses, embs):
                out[i] = e
                if self.cache is not None and key is not None:
                    self.cache.put(key, e)
        return out

    # -- level 2: the unit score ---------------------------------------------

    def score_unit(self, fns: Sequence[UnitFunction], sg) -> dict:
        """Score one merged unit as ONE request: level-1 embeddings (cache-
        fronted, fused-kernel) composed by the call-graph GGNN into a unit
        score plus per-function attribution. ``sg`` is the unit's
        :class:`~deepdfa_tpu.cpg.interproc.Supergraph` (the scan already
        built it for the taint differential)."""
        import jax
        import jax.numpy as jnp

        if not fns:
            raise ValueError("score_unit needs at least one function")
        names = [fn.name for fn in fns]
        embs = self.embed_functions(fns)
        summaries = unit_summaries(sg, names)
        senders, receivers = unit_call_edges(sg, names)
        x = jnp.concatenate(
            [jnp.asarray(embs), jnp.asarray(summaries)], axis=-1)
        mask = jnp.ones(len(fns), bool)
        unit_logit, fn_logit, gate = self._level2.apply(
            {"params": self._l2_params}, x, jnp.asarray(senders),
            jnp.asarray(receivers), mask)
        unit_p = float(jax.nn.sigmoid(unit_logit))
        fn_p = np.asarray(jax.nn.sigmoid(fn_logit), np.float32)
        gate = np.asarray(gate, np.float32)
        attribution = sorted(
            ({"function": name, "weight": round(float(w), 6),
              "score": round(float(p), 6)}
             for name, w, p in zip(names, gate, fn_p)),
            key=lambda row: -row["weight"])
        return {
            "unit_score": round(unit_p, 6),
            "attribution": attribution,
            "n_functions": len(fns),
            "call_edges": int(sg.n_call_edges),
            "level1": self.stats(),
        }

    # -- accounting -----------------------------------------------------------

    def stats(self) -> dict:
        return {
            "dispatches": self.n_level1_dispatches,
            "fallback_dispatches": self.n_fallback_dispatches,
            "recompute": self.level1_recompute,
            "cache": self.cache.stats() if self.cache is not None else None,
        }

    def reset_counters(self) -> None:
        self.n_level1_dispatches = 0
        self.n_fallback_dispatches = 0
        self.level1_recompute = 0
