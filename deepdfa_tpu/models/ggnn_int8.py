"""GGNN with int8-resident message-passing matmuls — the serving precision
path (``serve.precision=int8``).

Same model family as :class:`deepdfa_tpu.models.ggnn.GGNN` (subclass, same
``BatchedGraphs`` segment input, same embeddings/pooling/head), but every
conv matmul — ``edge_linear`` and the two fused 3-gate GRU projections —
runs through :func:`deepdfa_tpu.ops.int8_matmul.int8_matmul` against int8
weights with per-output-channel f32 scales. At the serving bucket ladder
the hidden-32 conv matmuls are memory-bound, so halving weight bytes is a
straight bandwidth win (ROADMAP direction 2b).

The int8 conv is inference-only: ``int8_matmul`` is differentiable w.r.t.
activations only (frozen-base convention), and the serving engine is the
only caller. Embeddings, pooling, and the classifier head stay f32 —
they are gathers and tiny [out_in, 1]-ish matmuls where quantisation buys
nothing and costs accuracy.

Weights are NOT trained in int8: :func:`quantize_conv_params` calibrates
an existing f32 checkpoint tree at engine build time (symmetric absmax via
:func:`~deepdfa_tpu.ops.int8_matmul.calibrate_int8`), producing the
``{q, scale, bias}`` leaves this model consumes. The engine gates the
result against f32 scores before serving it (``serve.int8_max_score_delta``).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from deepdfa_tpu.models.ggnn import GGNN
from deepdfa_tpu.ops.int8_matmul import calibrate_int8, int8_matmul
from deepdfa_tpu.ops.segment import gather, segment_sum

__all__ = ["GGNNInt8", "GatedGraphConvInt8", "quantize_conv_params"]

# conv param leaves replaced by quantize_conv_params, relative to the model's
# "ggnn" scope — everything else in the tree passes through untouched
_CONV_DENSE_PATHS = (
    ("edge_linear",),
    ("gru", "x_proj"),
    ("gru", "h_proj"),
)


class _Int8Dense(nn.Module):
    """Parameter container for one quantized Dense: ``q`` int8 ``[K, N]``,
    ``scale`` f32 ``[N]``, ``bias`` f32 ``[N]`` (the ``QuantizedLeaf``
    layout plus the bias, which stays f32 — it adds post-scale). Inits are
    placeholders (zeros/ones): real values always come from
    :func:`quantize_conv_params` on a trained f32 tree."""

    in_features: int
    features: int

    def setup(self):
        self.q = self.param(
            "q", nn.initializers.zeros_init(),
            (self.in_features, self.features), jnp.int8,
        )
        self.scale = self.param(
            "scale", nn.initializers.ones_init(), (self.features,), jnp.float32
        )
        self.bias = self.param(
            "bias", nn.initializers.zeros_init(), (self.features,), jnp.float32
        )

    def __call__(self, x: jnp.ndarray, *, interpret: bool) -> jnp.ndarray:
        # hidden widths here are 128-ish: 128-cubed blocks avoid the LLM
        # default block_k=512 padding 4x along K
        return int8_matmul(
            x, self.q, self.scale,
            block_m=128, block_n=128, block_k=128,
            out_dtype=jnp.float32, interpret=interpret,
        ) + self.bias


class _Int8GRU(nn.Module):
    """GRUCell's tree with both fused 3-gate projections int8-resident."""

    features: int

    def setup(self):
        self.x_proj = _Int8Dense(self.features, 3 * self.features)
        self.h_proj = _Int8Dense(self.features, 3 * self.features)

    def __call__(self, x, h, *, interpret: bool) -> jnp.ndarray:
        xp = self.x_proj(x, interpret=interpret)
        hp = self.h_proj(h, interpret=interpret)
        xr, xz, xn = jnp.split(xp, 3, axis=-1)
        hr, hz, hn = jnp.split(hp, 3, axis=-1)
        r = nn.sigmoid(xr + hr)
        z = nn.sigmoid(xz + hz)
        n = jnp.tanh(xn + r * hn)
        return (1.0 - z) * n + z * h


class GatedGraphConvInt8(nn.Module):
    """Segment-layout :class:`GatedGraphConv` (sum aggregation) with the
    three conv matmuls int8-resident. Scope names (``edge_linear``,
    ``gru/{x_proj,h_proj}``) mirror the f32 layouts so
    :func:`quantize_conv_params` maps leaves 1:1.

    ``interpret``: None auto-selects the Pallas interpreter off-TPU,
    exactly like the fused layout.
    """

    out_feats: int
    n_steps: int
    aggregation: str = "sum"
    edges_sorted: bool = True
    dtype: Any = jnp.float32
    interpret: bool | None = None

    def setup(self):
        if self.aggregation != "sum":
            raise ValueError(
                f"precision=int8 supports aggregation='sum' only; got "
                f"{self.aggregation!r} — serve the union-lattice aggregators "
                f"at f32"
            )
        self.edge_linear = _Int8Dense(self.out_feats, self.out_feats)
        self.gru = _Int8GRU(self.out_feats)

    def __call__(
        self, h: jnp.ndarray, senders: jnp.ndarray, receivers: jnp.ndarray,
        taps: tuple | None = None,
    ) -> jnp.ndarray:
        if taps is not None:
            raise ValueError(
                "per-step taps are a training diagnostic — the int8 conv is "
                "a serving path (use layout=segment at f32)"
            )
        n_nodes = h.shape[0]
        if self.edges_sorted and not isinstance(receivers, jax.core.Tracer):
            r = np.asarray(receivers)
            if r.size and np.any(np.diff(r) < 0):
                raise ValueError(
                    "edges_sorted=True but receivers are not sorted by "
                    "receiver — pass edges_sorted=False for hand-built edge "
                    "lists, or sort them (batch_np does this on the host)"
                )
        if h.shape[-1] > self.out_feats:
            raise ValueError("in_feats must be <= out_feats (DGL contract)")
        if h.shape[-1] < self.out_feats:
            pad = jnp.zeros((n_nodes, self.out_feats - h.shape[-1]), h.dtype)
            h = jnp.concatenate([h, pad], axis=-1)
        interpret = self.interpret
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        h = h.astype(jnp.float32)
        for _step in range(self.n_steps):
            msg_src = self.edge_linear(h, interpret=interpret)
            agg = segment_sum(gather(msg_src, senders), receivers, n_nodes,
                              indices_are_sorted=self.edges_sorted)
            h = self.gru(agg, h, interpret=interpret)
        return h.astype(self.dtype)


class GGNNInt8(GGNN):
    """:class:`GGNN` with the conv swapped for the int8-resident matmul
    path. Consumed only by the serving engine (``serve.precision=int8``)."""

    def _conv(self, hidden_dim: int) -> nn.Module:
        return GatedGraphConvInt8(
            out_feats=hidden_dim,
            n_steps=self.cfg.n_steps,
            aggregation=self.cfg.aggregation,
            dtype=self.compute_dtype,
        )


def quantize_conv_params(variables: dict) -> dict:
    """Calibrate a trained f32 variables tree into the :class:`GGNNInt8`
    tree: for each conv Dense (``ggnn/edge_linear``, ``ggnn/gru/x_proj``,
    ``ggnn/gru/h_proj``) the ``kernel`` leaf becomes ``{q, scale}`` via
    :func:`calibrate_int8`; biases and every other leaf (embeddings, pooling
    gate, head) pass through unchanged.

    Raises ``ValueError`` (propagated from ``calibrate_int8``) on non-finite
    kernels — a poisoned checkpoint must not be silently clamped into a
    serving artifact. Host-side, once per engine build.
    """
    params = dict(variables.get("params", variables))
    if "ggnn" not in params:
        raise ValueError(
            "quantize_conv_params: no 'ggnn' scope in params — expected a "
            "GGNN/GGNNFused variables tree"
        )

    def _q(dense: dict) -> dict:
        q, scale = calibrate_int8(dense["kernel"])
        return {"q": q, "scale": scale, "bias": jnp.asarray(dense["bias"], jnp.float32)}

    ggnn = dict(params["ggnn"])
    for path in _CONV_DENSE_PATHS:
        node = ggnn
        for key in path[:-1]:
            node[key] = dict(node[key])
            node = node[key]
        node[path[-1]] = _q(node[path[-1]])
    params["ggnn"] = ggnn
    if "params" in variables:
        out = dict(variables)
        out["params"] = params
        return out
    return params
