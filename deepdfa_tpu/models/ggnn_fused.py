"""GGNN with the Pallas VMEM-resident fused message-passing conv.

Same model as :class:`deepdfa_tpu.models.ggnn.GGNN` — it *is* a subclass
consuming the same segment-layout :class:`BatchedGraphs`, with an identical
parameter tree (the conv's param containers reproduce ``nn.Dense``'s
``{kernel, bias}`` leaves under the same ``ggnn/edge_linear`` and
``ggnn/gru/{x,h}_proj`` scopes, with the same initialisers, so fresh inits
are bit-identical and checkpoints interchange across all three layouts) —
but the unrolled conv runs as ONE Pallas kernel with node states resident
in VMEM across all ``n_steps`` rounds (:mod:`deepdfa_tpu.ops.fused_ggnn`),
instead of ``n_steps`` dispatches of gather + ``segment_sum`` + GRU.

Embedding lookup, attention pooling, and the classifier head are inherited
unchanged: only the scatter-bound middle is swapped. Parity with the
segment forward is asserted by ``tests/test_fused_ggnn.py`` on shared
parameters (forward ≤1e-5, gradients through the ``custom_vjp``).

Trade-off vs the dense layout: fused keeps O(Ed) FLOPs (no n² adjacency)
and the segment batch pipeline, but requires the per-bucket working set to
fit VMEM — the :class:`~deepdfa_tpu.train.loop.Trainer` routes oversized
buckets through its segment-twin fallback, exactly like the dense layout's
overflow handling.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from deepdfa_tpu.models.ggnn import GGNN
from deepdfa_tpu.ops.fused_ggnn import fused_ggnn

__all__ = ["GGNNFused", "GatedGraphConvFused"]


class _DenseParams(nn.Module):
    """Parameter container replicating ``nn.Dense``'s param leaves (same
    names, shapes, initialisers, f32 param dtype) without the apply logic —
    the fused kernel consumes the raw arrays. Identical scope paths + init
    fns make fresh inits bit-identical to the segment/dense layouts."""

    in_features: int
    features: int

    def setup(self):
        self.kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (self.in_features, self.features), jnp.float32,
        )
        self.bias = self.param(
            "bias", nn.initializers.zeros_init(), (self.features,), jnp.float32
        )


class _GRUParams(nn.Module):
    """``GRUCell``'s parameter tree (fused 3-gate x/h projections)."""

    features: int

    def setup(self):
        self.x_proj = _DenseParams(self.features, 3 * self.features)
        self.h_proj = _DenseParams(self.features, 3 * self.features)


class GatedGraphConvFused(nn.Module):
    """Drop-in for :class:`GatedGraphConv` (sum aggregation) backed by the
    single-kernel VMEM-resident forward.

    ``interpret``: None (default) auto-selects — Pallas interpreter on
    non-TPU backends so the CPU suite exercises the real kernel; compiled
    Mosaic on TPU. The union-lattice aggregators and per-step ``taps``
    diagnostics are segment/dense-layout features; requesting them here
    raises rather than silently diverging.
    """

    out_feats: int
    n_steps: int
    aggregation: str = "sum"
    edges_sorted: bool = True
    dtype: Any = jnp.float32
    interpret: bool | None = None
    # backward tier: "auto" picks the fused Pallas training kernel when
    # fits_vmem_train admits the bucket, else the XLA recompute backward
    bwd_kernel: str = "auto"

    def setup(self):
        if self.aggregation != "sum":
            raise ValueError(
                f"layout=fused supports aggregation='sum' only (DGL parity "
                f"path); got {self.aggregation!r} — use layout=segment for "
                f"the union-lattice aggregators"
            )
        self.edge_linear = _DenseParams(self.out_feats, self.out_feats)
        self.gru = _GRUParams(self.out_feats)

    def __call__(
        self, h: jnp.ndarray, senders: jnp.ndarray, receivers: jnp.ndarray,
        taps: tuple | None = None,
    ) -> jnp.ndarray:
        if taps is not None:
            raise ValueError(
                "per-step taps are a segment-layout diagnostic — the fused "
                "kernel does not materialise per-round states (use "
                "layout=segment for tap-based gradient probes)"
            )
        # same eager receiver-sort validation as GatedGraphConv: a false
        # edges_sorted promise makes the backward's sorted segment sum
        # silently wrong
        if self.edges_sorted and not isinstance(receivers, jax.core.Tracer):
            r = np.asarray(receivers)
            if r.size and np.any(np.diff(r) < 0):
                raise ValueError(
                    "edges_sorted=True but receivers are not sorted by "
                    "receiver — pass edges_sorted=False for hand-built edge "
                    "lists, or sort them (batch_np does this on the host)"
                )
        if h.shape[-1] > self.out_feats:
            raise ValueError("in_feats must be <= out_feats (DGL contract)")
        if h.shape[-1] < self.out_feats:
            pad = jnp.zeros((h.shape[0], self.out_feats - h.shape[-1]), h.dtype)
            h = jnp.concatenate([h, pad], axis=-1)
        interpret = self.interpret
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        out = fused_ggnn(
            h,
            senders,
            receivers,
            self.edge_linear.kernel,
            self.edge_linear.bias,
            self.gru.x_proj.kernel,
            self.gru.x_proj.bias,
            self.gru.h_proj.kernel,
            self.gru.h_proj.bias,
            n_steps=self.n_steps,
            interpret=interpret,
            edges_sorted=self.edges_sorted,
            bwd_kernel=self.bwd_kernel,
        )
        return out.astype(self.dtype)


class GGNNFused(GGNN):
    """:class:`GGNN` with the conv swapped for the fused Pallas kernel
    (``model.layout=fused``). Everything else — embeddings, pooling, head,
    the ``BatchedGraphs`` input contract — is inherited."""

    def _conv(self, hidden_dim: int) -> nn.Module:
        return GatedGraphConvFused(
            out_feats=hidden_dim,
            n_steps=self.cfg.n_steps,
            aggregation=self.cfg.aggregation,
            dtype=self.compute_dtype,
            bwd_kernel=getattr(self.cfg, "bwd_kernel", "auto"),
        )
