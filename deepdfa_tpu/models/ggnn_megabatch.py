"""GGNN with the whole-model fused Pallas forward (``layout=megabatch``).

Same model as :class:`deepdfa_tpu.models.ggnn.GGNN` over the same
segment-layout :class:`BatchedGraphs`, with an identical parameter tree
(every container reproduces ``nn.Dense``/``nn.Embed`` leaves under the
same scopes with the same initialisers, so fresh inits are bit-identical
and checkpoints interchange across all four layouts) — but the ENTIRE
forward (embed → messages → GRU → attention pool → label head) runs as ONE
Pallas launch (:func:`deepdfa_tpu.ops.megabatch.fused_ggnn_model`). The
fused layout already removed the per-round dispatches; this removes the
pooling and head dispatches too, which is what megabatch packing needs:
one launch per packed megabatch instead of a ladder of per-bucket ones.

Routing is static per bucket shape: if the megabatch VMEM plan
(:func:`fits_vmem_megabatch`) refuses the shape, ``__call__`` computes via
:func:`megabatch_reference` — plain XLA segment ops, operation-for-
operation the segment layout's math, so the fallback is bit-identical to
the segment twin on the same params (pinned by ``tests/test_megabatch.py``).

The whole-model kernel hard-codes the flagship configuration: concat-
subkey abstract-dataflow embeddings (embed width == hidden width), sum
aggregation, graph-level labels, classifier head. The excluded variants
(``dataflow_families``, union aggregators, ``label_style="node"``,
``encoder_mode``) raise at construction — use ``layout=segment`` (or
``fused``) for those; silently diverging would be worse.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepdfa_tpu.config import ALL_SUBKEYS
from deepdfa_tpu.data.graphs import BatchedGraphs
from deepdfa_tpu.models.ggnn import GGNN
from deepdfa_tpu.models.ggnn_fused import GatedGraphConvFused, _DenseParams
from deepdfa_tpu.ops.megabatch import (
    MegabatchPlan,
    fused_ggnn_model,
    megabatch_reference,
)

__all__ = ["GGNNMegabatch"]


class _PoolingParams(nn.Module):
    """``GlobalAttentionPooling``'s parameter tree (the ``gate`` Dense)
    without the apply logic — the whole-model kernel consumes the raw
    arrays. Same scope path + init fns keep fresh inits bit-identical."""

    in_features: int

    def setup(self):
        self.gate = _DenseParams(self.in_features, 1)


class GGNNMegabatch(GGNN):
    """:class:`GGNN` computed in one whole-model Pallas launch
    (``model.layout=megabatch``), with bit-identical segment-twin routing
    for shapes the VMEM plan refuses."""

    def setup(self):
        cfg = self.cfg
        if not cfg.concat_all_absdf or cfg.dataflow_families or cfg.interproc_families:
            raise ValueError(
                "layout=megabatch supports the concat-subkey abstract-"
                "dataflow config only (concat_all_absdf=True, "
                "dataflow_families=False, interproc_families=False) — the "
                "whole-model kernel's embed prologue hard-codes the "
                "stacked-table gather; use layout=segment/fused for other "
                "embedding configs"
            )
        if cfg.label_style != "graph" or cfg.encoder_mode:
            raise ValueError(
                "layout=megabatch supports graph-level classification only "
                "(label_style='graph', encoder_mode=False) — the fused "
                "epilogue IS the pooling+head; use layout=segment otherwise"
            )
        if cfg.aggregation != "sum":
            raise ValueError(
                f"layout=megabatch supports aggregation='sum' only; got "
                f"{cfg.aggregation!r} — use layout=segment for the "
                "union-lattice aggregators"
            )
        self.compute_dtype = jnp.dtype(cfg.dtype)
        self.embeddings = {
            sk: nn.Embed(
                self.input_dim,
                cfg.hidden_dim,
                dtype=self.compute_dtype,
                name=f"embed_{sk}",
            )
            for sk in ALL_SUBKEYS
        }
        hidden_dim = cfg.hidden_dim * len(ALL_SUBKEYS)
        self.ggnn = GatedGraphConvFused(
            out_feats=hidden_dim,
            n_steps=cfg.n_steps,
            aggregation=cfg.aggregation,
            dtype=self.compute_dtype,
            bwd_kernel=getattr(cfg, "bwd_kernel", "auto"),
        )
        out_in = 2 * hidden_dim
        self.pooling = _PoolingParams(out_in)
        self.head = [
            _DenseParams(
                out_in,
                1 if i == cfg.num_output_layers - 1 else out_in,
                name=f"out_{i}",
            )
            for i in range(cfg.num_output_layers)
        ]

    def plan_for(self, max_nodes: int, max_edges: int,
                 max_graphs: int) -> MegabatchPlan:
        """The static VMEM plan for a bucket shape (what routing consults)."""
        return MegabatchPlan(
            max_graphs=max_graphs,
            max_nodes=max_nodes,
            max_edges=max_edges,
            width=self.cfg.hidden_dim * len(ALL_SUBKEYS),
            n_steps=self.cfg.n_steps,
            table_rows=self.input_dim * len(ALL_SUBKEYS),
            embed_width=self.cfg.hidden_dim,
            n_head_layers=self.cfg.num_output_layers,
        )

    def __call__(self, batch: BatchedGraphs, taps: tuple | None = None) -> jnp.ndarray:
        if taps is not None:
            raise ValueError(
                "per-step taps are a segment-layout diagnostic — the whole-"
                "model kernel does not materialise per-round states (use "
                "layout=segment for tap-based gradient probes)"
            )
        cfg = self.cfg
        ct = self.compute_dtype
        table = jnp.concatenate(
            [self.embeddings[sk].embedding for sk in ALL_SUBKEYS], axis=0
        ).astype(ct)
        ids = jnp.stack(
            [
                batch.node_feats[f"_ABS_DATAFLOW_{sk}"] + i * self.input_dim
                for i, sk in enumerate(ALL_SUBKEYS)
            ],
            axis=-1,
        )
        conv = self.ggnn
        ew, eb = conv.edge_linear.kernel, conv.edge_linear.bias
        xw, xb = conv.gru.x_proj.kernel, conv.gru.x_proj.bias
        hw, hb = conv.gru.h_proj.kernel, conv.gru.h_proj.bias
        gw, gb = self.pooling.gate.kernel, self.pooling.gate.bias
        head = tuple((layer.kernel, layer.bias) for layer in self.head)
        plan = self.plan_for(batch.max_nodes, batch.senders.shape[0],
                             batch.max_graphs)
        if plan.fits:
            interpret = jax.default_backend() != "tpu"
            return fused_ggnn_model(
                table, ids, batch.senders, batch.receivers,
                batch.node_gidx, batch.node_mask,
                ew, eb, xw, xb, hw, hb, gw, gb, head,
                n_steps=cfg.n_steps, n_graphs=batch.max_graphs,
                interpret=interpret, edges_sorted=True,
            )
        # over-plan: bit-identical segment-twin math, same params
        return megabatch_reference(
            table, ids, batch.senders, batch.receivers,
            batch.node_gidx, batch.node_mask,
            ew, eb, xw, xb, hw, hb, gw, gb, head,
            n_steps=cfg.n_steps, n_graphs=batch.max_graphs,
            edges_sorted=True,
        )
