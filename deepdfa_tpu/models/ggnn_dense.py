"""GGNN over dense per-graph adjacency: message passing as batched matmuls.

Same model as :class:`deepdfa_tpu.models.ggnn.GGNN` — identical parameter
tree (submodule names match, so checkpoints are interchangeable between the
two forwards) — but the graph is a ``[G, n, n]`` dense adjacency instead of
flat edge lists, and one step of message passing is

    ``agg = einsum('gji,gjd->gid', adj, msg)``

a batched matmul the MXU executes at full tilt, replacing the
gather + scatter-add chain (which on TPU runs through the VPU scatter path
and bounded the segment-layout bench at ~3% of the matmul ceiling). The
union-lattice aggregators become matmuls too:

- ``union_relu``:   ``min(1, σh + adj^T σm)`` — same einsum;
- ``union_simple``: ``1 - (1-σh) · exp(adj^T log(1-σm))`` — the iterated
  product over incoming edges turns into a matmul in log space (duplicate
  edges contribute their count, exactly like repeated segment entries).

Reference semantics preserved (DGL ``GatedGraphConv`` + attention pooling,
``flow_gnn/ggnn.py:22-109``, union fold ``clipper.py:50-77``); parity with
the segment-layout forward is asserted by ``tests/test_ggnn_dense.py`` on
shared parameters. Trade-off: O(n²d) FLOPs instead of O(Ed) — a dozen
extra MFLOPs per graph at n≈64, bought at matmul speed; padding nodes are
inert (zero adjacency rows/cols, masked out of pooling).

The dense-block pattern follows the public sparse-GNN-on-dense-hardware
recipe (arXiv:1906.11786), applied per-graph because CFGs are tiny.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from deepdfa_tpu.config import (
    ALL_SUBKEYS,
    DFA_FEATURE_DIMS,
    GGNNConfig,
    active_dfa_families,
)
from deepdfa_tpu.data.dense import DenseBatch
from deepdfa_tpu.models.ggnn import GRUCell

__all__ = ["GGNNDense"]


class GatedGraphConvDense(nn.Module):
    """n_steps of (linear → adjacency matmul → GRU) on ``[G, n, d]`` states."""

    out_feats: int
    n_steps: int
    aggregation: str = "sum"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, h: jnp.ndarray, adj: jnp.ndarray) -> jnp.ndarray:
        if h.shape[-1] > self.out_feats:
            raise ValueError("in_feats must be <= out_feats (DGL contract)")
        if h.shape[-1] < self.out_feats:
            pad = jnp.zeros((*h.shape[:-1], self.out_feats - h.shape[-1]), h.dtype)
            h = jnp.concatenate([h, pad], axis=-1)
        if self.aggregation not in ("sum", "union_simple", "union_relu"):
            raise ValueError(f"unknown aggregation {self.aggregation!r}")
        edge_linear = nn.Dense(self.out_feats, dtype=self.dtype, name="edge_linear")
        gru = GRUCell(self.out_feats, dtype=self.dtype, name="gru")
        adj = adj.astype(self.dtype)
        for _ in range(self.n_steps):
            msg = edge_linear(h)
            if self.aggregation == "sum":
                agg = jnp.einsum("gji,gjd->gid", adj, msg)
            elif self.aggregation == "union_relu":
                total = jnp.einsum("gji,gjd->gid", adj, nn.sigmoid(msg))
                agg = 1.0 - jnp.maximum(1.0 - (nn.sigmoid(h) + total), 0.0)
            else:  # union_simple
                m = nn.sigmoid(msg)
                tiny = jnp.finfo(jnp.float32).tiny
                logs = jnp.log(jnp.maximum(1.0 - m, tiny).astype(jnp.float32))
                logsum = jnp.einsum("gji,gjd->gid", adj.astype(jnp.float32), logs)
                # Exact-zero parity with the segment fold: a saturated
                # message (σm == 1) zeroes the product there, while the
                # log-space matmul bottoms out at exp(log(tiny)·k) ≈ 1e-38 —
                # flush any sum at/below log(tiny) to a true 0 (a genuine
                # product that small underflows to 0 anyway, so the flush
                # only ever makes the result MORE accurate).
                prod = jnp.where(
                    logsum <= jnp.log(tiny), 0.0, jnp.exp(logsum)
                ).astype(h.dtype)
                agg = 1.0 - (1.0 - nn.sigmoid(h)) * prod
            h = gru(agg, h)
        return h


class GlobalAttentionPoolingDense(nn.Module):
    """Masked softmax attention readout over the node axis of ``[G, n, d]``."""

    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, h: jnp.ndarray, node_mask: jnp.ndarray) -> jnp.ndarray:
        gate_logit = nn.Dense(1, dtype=self.dtype, name="gate")(h)[..., 0]
        neg = jnp.asarray(-jnp.inf, gate_logit.dtype)
        gate_logit = jnp.where(node_mask, gate_logit, neg)
        gate_logit = gate_logit - jnp.max(
            jnp.where(node_mask, gate_logit, -1e30), axis=1, keepdims=True
        )
        exp = jnp.where(node_mask, jnp.exp(gate_logit), 0.0)
        denom = jnp.sum(exp, axis=1, keepdims=True)
        gate = exp / jnp.where(denom == 0, 1.0, denom)
        return jnp.einsum("gn,gnd->gd", gate.astype(h.dtype), h)


class GGNNDense(nn.Module):
    """Dense-layout forward of the flagship model. Parameter tree is
    identical to :class:`GGNN` — init either module and apply with the
    other's params."""

    cfg: GGNNConfig
    input_dim: int

    def setup(self):
        cfg = self.cfg
        self.compute_dtype = jnp.dtype(cfg.dtype)
        embed_dim = cfg.hidden_dim
        if cfg.concat_all_absdf:
            self.embeddings = {
                sk: nn.Embed(
                    self.input_dim, embed_dim, dtype=self.compute_dtype,
                    name=f"embed_{sk}",
                )
                for sk in ALL_SUBKEYS
            }
            embed_dim *= len(ALL_SUBKEYS)
            hidden_dim = cfg.hidden_dim * len(ALL_SUBKEYS)
        else:
            self.embedding = nn.Embed(
                self.input_dim, embed_dim, dtype=self.compute_dtype, name="embed"
            )
            hidden_dim = cfg.hidden_dim
        fams = active_dfa_families(cfg.dataflow_families, cfg.interproc_families)
        if fams:
            # lockstep with GGNN.setup — same table names/shapes so the
            # parameter trees stay checkpoint-interchangeable
            self.dfa_embeddings = {
                fam: nn.Embed(
                    DFA_FEATURE_DIMS[fam],
                    cfg.hidden_dim,
                    dtype=self.compute_dtype,
                    name=f"embed_dfa_{fam}",
                )
                for fam in fams
            }
            embed_dim += cfg.hidden_dim * len(fams)
            hidden_dim += cfg.hidden_dim * len(fams)
        self.ggnn = GatedGraphConvDense(
            out_feats=hidden_dim,
            n_steps=cfg.n_steps,
            aggregation=cfg.aggregation,
            dtype=self.compute_dtype,
        )
        out_in = embed_dim + hidden_dim
        if cfg.label_style == "graph":
            self.pooling = GlobalAttentionPoolingDense(dtype=self.compute_dtype)
        if not cfg.encoder_mode:
            self.head = [
                nn.Dense(
                    1 if i == cfg.num_output_layers - 1 else out_in,
                    dtype=self.compute_dtype,
                    name=f"out_{i}",
                )
                for i in range(cfg.num_output_layers)
            ]

    def _embed_dfa(self, batch: DenseBatch) -> jnp.ndarray:
        # lockstep with GGNN._embed_dfa, shapes [G, n] instead of [N]
        fams = active_dfa_families(
            self.cfg.dataflow_families, self.cfg.interproc_families
        )
        table = jnp.concatenate(
            [self.dfa_embeddings[fam].embedding for fam in fams], axis=0
        ).astype(self.compute_dtype)
        ids_cols = []
        offset = 0
        for fam in fams:
            ids_cols.append(batch.node_feats[f"_DFA_{fam}"] + offset)
            offset += DFA_FEATURE_DIMS[fam]
        ids = jnp.stack(ids_cols, axis=-1)
        out = jnp.take(table, ids, axis=0)
        return out.reshape(*ids.shape[:-1], -1)

    def embed_nodes(self, batch: DenseBatch) -> jnp.ndarray:
        if self.cfg.concat_all_absdf:
            # fused single gather across the 4 stacked subkey tables (same
            # trick as GGNN.embed_nodes, shapes [G, n] instead of [N])
            table = jnp.concatenate(
                [self.embeddings[sk].embedding for sk in ALL_SUBKEYS], axis=0
            ).astype(self.compute_dtype)
            ids = jnp.stack(
                [
                    batch.node_feats[f"_ABS_DATAFLOW_{sk}"] + i * self.input_dim
                    for i, sk in enumerate(ALL_SUBKEYS)
                ],
                axis=-1,
            )
            out = jnp.take(table, ids, axis=0)
            out = out.reshape(*ids.shape[:-1], -1)
        else:
            out = self.embedding(batch.node_feats["_ABS_DATAFLOW"])
        if self.cfg.dataflow_families or self.cfg.interproc_families:
            out = jnp.concatenate([out, self._embed_dfa(batch)], axis=-1)
        return out

    def __call__(self, batch: DenseBatch) -> jnp.ndarray:
        cfg = self.cfg
        feat_embed = self.embed_nodes(batch)  # [G, n, e]
        ggnn_out = self.ggnn(feat_embed, jnp.asarray(batch.adj))
        out = jnp.concatenate([ggnn_out, feat_embed], axis=-1)
        if cfg.label_style == "graph":
            out = self.pooling(out, jnp.asarray(batch.node_mask))
        if cfg.encoder_mode:
            return out
        for i, layer in enumerate(self.head):
            out = layer(out)
            if i != len(self.head) - 1:
                out = nn.relu(out)
        return out[..., 0].astype(jnp.float32)
