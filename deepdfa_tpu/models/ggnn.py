"""Gated Graph Neural Network over batched CFGs, in Flax.

Re-implements the semantics of the reference's ``FlowGNNGGNNModule``
(``DDFA/code_gnn/models/flow_gnn/ggnn.py:22-109``), which stacked DGL's
``GatedGraphConv`` (C++/CUDA SpMM kernels) and ``GlobalAttentionPooling`` —
here everything is XLA: embeddings and the GRU/linear matmuls hit the MXU,
message passing is gather + ``segment_sum``, attention pooling is a masked
segment softmax. Shapes are static (padded batches), so the whole forward
jits once per bucket.

Exact parity notes (validated by ``tests/test_ggnn_parity.py`` against a
torch scatter-add reference implementation of the DGL ops):

- ``GatedGraphConv`` applies a per-edge-type Linear (with bias) to the
  **source** state, sums incoming messages, then a GRU cell update; input
  features are zero-padded from ``in_feats`` to ``out_feats``. With
  ``n_etypes=1`` the per-edge Linear commutes to a per-node Linear before the
  gather (identical math, one matmul instead of |E|).
- ``GlobalAttentionPooling(gate_nn=Linear(d,1))``: softmax of the gate over
  nodes *within each graph*, then weighted sum of node states.
- Per-subkey embedding tables are concatenated when ``concat_all_absdf``
  (``ggnn.py:47-54``): embed and hidden widths each ×4.
- The classifier input is ``concat([ggnn_out, feat_embed])``
  (``ggnn.py:98``); ``encoder_mode`` returns the pooled embedding for LLM
  fusion (``ggnn.py:104-107``).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from deepdfa_tpu.config import (
    ALL_SUBKEYS,
    DFA_FEATURE_DIMS,
    GGNNConfig,
    active_dfa_families,
)
from deepdfa_tpu.data.graphs import BatchedGraphs
from deepdfa_tpu.ops.segment import gather, segment_softmax, segment_sum

__all__ = ["GGNN", "GRUCell"]


class GRUCell(nn.Module):
    """GRU cell with torch ``nn.GRUCell`` gate layout (reset/update/new), the
    update rule DGL's GatedGraphConv uses. ``features`` is the hidden width.

    The three per-gate projections of each input are fused into ONE
    ``(features → 3·features)`` matmul per input (columns ordered ``r|z|n`` —
    exactly torch's ``weight_ih``/``weight_hh`` row layout, transposed), so a
    step costs 2 MXU-shaped matmuls instead of 6 slivers. Per-output-element
    math is unchanged: fusing along the output axis does not reorder any
    reduction."""

    features: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, h: jnp.ndarray) -> jnp.ndarray:
        xp = nn.Dense(3 * self.features, dtype=self.dtype, name="x_proj")(x)
        hp = nn.Dense(3 * self.features, dtype=self.dtype, name="h_proj")(h)
        xr, xz, xn = jnp.split(xp, 3, axis=-1)
        hr, hz, hn = jnp.split(hp, 3, axis=-1)
        r = nn.sigmoid(xr + hr)
        z = nn.sigmoid(xz + hz)
        n = jnp.tanh(xn + r * hn)
        return (1.0 - z) * n + z * h


class GatedGraphConv(nn.Module):
    """n_steps of (linear → gather(senders) → aggregate(receivers) → GRU).

    Self-loop edges are expected in the data (added at materialisation time,
    parity with ``dbize_graphs.py:26``).

    ``aggregation``: ``"sum"`` (DGL ``GatedGraphConv`` parity) or the
    differentiable set unions ``"union_simple"``/``"union_relu"`` — the
    "learn the DFA lattice" aggregators (``clipper.py:50-77``; mailbox fold
    replaced by closed-form segment ops, ``ops/union.py``). Union
    aggregation treats messages as soft membership bits, matching the
    reaching-definitions meet operator ∪.

    ``edges_sorted``: whether edges arrive sorted by receiver. True is the
    ``batch_np`` contract (every batch in this framework) and lets each
    scatter-add take XLA's sorted-segment fast path. Callers feeding
    hand-built edge lists that are NOT receiver-sorted MUST pass False —
    a false promise makes TPU segment reductions silently wrong.
    """

    out_feats: int
    n_steps: int
    aggregation: str = "sum"
    edges_sorted: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(
        self, h: jnp.ndarray, senders: jnp.ndarray, receivers: jnp.ndarray,
        taps: tuple | None = None,
    ) -> jnp.ndarray:
        """``taps`` (diagnostics only): a tuple of ``n_steps`` zero arrays
        shaped like ``h`` added to the state after each GRU update — the
        standard trick for reading per-step gradients dL/dh_t through the
        unrolled chain (grad w.r.t. taps[t]); None (the default) changes
        nothing."""
        n_nodes = h.shape[0]
        # A false edges_sorted promise makes TPU segment reductions silently
        # wrong; when running eagerly (tests, hand-built batches — concrete
        # arrays, not tracers) verify it. Jitted callers (Trainer) pass
        # batch_np output, whose contract is host-side receiver sort.
        if self.edges_sorted and not isinstance(receivers, jax.core.Tracer):
            r = np.asarray(receivers)
            if r.size and np.any(np.diff(r) < 0):
                raise ValueError(
                    "edges_sorted=True but receivers are not sorted by "
                    "receiver — pass edges_sorted=False for hand-built edge "
                    "lists, or sort them (batch_np does this on the host)"
                )
        if h.shape[-1] > self.out_feats:
            raise ValueError("in_feats must be <= out_feats (DGL contract)")
        if h.shape[-1] < self.out_feats:
            pad = jnp.zeros((n_nodes, self.out_feats - h.shape[-1]), h.dtype)
            h = jnp.concatenate([h, pad], axis=-1)
        edge_linear = nn.Dense(self.out_feats, dtype=self.dtype, name="edge_linear")
        gru = GRUCell(self.out_feats, dtype=self.dtype, name="gru")
        if self.aggregation not in ("sum", "union_simple", "union_relu"):
            raise ValueError(f"unknown aggregation {self.aggregation!r}")
        if self.aggregation != "sum":
            from deepdfa_tpu.ops.union import segment_union_relu, segment_union_simple

            union = (
                segment_union_simple
                if self.aggregation == "union_simple"
                else segment_union_relu
            )
        # Edges arrive sorted by receiver — the ``batch_np`` contract (see
        # ``BatchedGraphs``) — so every scatter-add in the unrolled chain runs
        # XLA's sorted-segment fast path with NO device-side argsort: the
        # O(E log² E) bitonic sort this used to pay per jitted forward now
        # happens once per batch as a numpy argsort on the host.
        # Python loop, unrolled by trace: n_steps is small (5) and static;
        # unrolling lets XLA pipeline the matmuls instead of a lax.scan barrier.
        for _step in range(self.n_steps):
            msg_src = edge_linear(h)
            if self.aggregation == "sum":
                agg = segment_sum(gather(msg_src, senders), receivers, n_nodes,
                                  indices_are_sorted=self.edges_sorted)
            else:
                # union space is [0,1] soft membership: messages AND the
                # node's own state map through sigmoid (the reference fold
                # starts from ``nodes.data["h"]``, clipper.py:70-73, with h
                # living in bit space in its experiments; sigmoid keeps the
                # union algebra valid for our unconstrained GRU state and
                # matches exactly at saturation)
                msgs = nn.sigmoid(msg_src)
                agg = union(nn.sigmoid(h), msgs, senders, receivers,
                            indices_are_sorted=self.edges_sorted)
            h = gru(agg, h)
            if taps is not None:
                h = h + taps[_step]
        return h


class GlobalAttentionPooling(nn.Module):
    """Masked segment-softmax attention readout (DGL ``GlobalAttentionPooling``
    with ``gate_nn=Linear(d, 1)`` and no feat_nn, parity ``ggnn.py:66-68``)."""

    dtype: Any = jnp.float32

    @nn.compact
    def __call__(
        self,
        h: jnp.ndarray,
        node_gidx: jnp.ndarray,
        node_mask: jnp.ndarray,
        num_graphs: int,
    ) -> jnp.ndarray:
        gate_logit = nn.Dense(1, dtype=self.dtype, name="gate")(h)[:, 0]
        # node_gidx is non-decreasing by construction (batch_np concatenates
        # graphs in order), so every readout scatter takes the sorted fast path
        gate = segment_softmax(gate_logit, node_gidx, num_graphs, mask=node_mask,
                               indices_are_sorted=True)
        # statement saliency for `predict`: which nodes the readout weighted.
        # sow is a no-op unless the caller applies with
        # mutable=["intermediates"] — training/inference paths are unchanged.
        self.sow("intermediates", "gate_weights", gate)
        return segment_sum(gate[:, None] * h, node_gidx, num_graphs,
                           indices_are_sorted=True)


class GGNN(nn.Module):
    """The flagship DeepDFA model: abstract-dataflow embeddings → GGNN →
    attention pooling → MLP classifier (or pooled embedding in encoder mode).
    """

    cfg: GGNNConfig
    input_dim: int

    def setup(self):
        cfg = self.cfg
        self.compute_dtype = jnp.dtype(cfg.dtype)
        embed_dim = cfg.hidden_dim
        if cfg.concat_all_absdf:
            self.embeddings = {
                sk: nn.Embed(
                    self.input_dim,
                    embed_dim,
                    dtype=self.compute_dtype,
                    name=f"embed_{sk}",
                )
                for sk in ALL_SUBKEYS
            }
            embed_dim *= len(ALL_SUBKEYS)
            hidden_dim = cfg.hidden_dim * len(ALL_SUBKEYS)
        else:
            self.embedding = nn.Embed(
                self.input_dim, embed_dim, dtype=self.compute_dtype, name="embed"
            )
            hidden_dim = cfg.hidden_dim
        fams = active_dfa_families(cfg.dataflow_families, cfg.interproc_families)
        if fams:
            # static-analysis families (liveness/uninit/taint, plus the
            # interprocedural ireach/itaint): small closed value sets, one
            # hidden_dim-wide table each, concatenated after the subkey
            # embeddings (widths from config.DFA_FEATURE_DIMS)
            self.dfa_embeddings = {
                fam: nn.Embed(
                    DFA_FEATURE_DIMS[fam],
                    cfg.hidden_dim,
                    dtype=self.compute_dtype,
                    name=f"embed_dfa_{fam}",
                )
                for fam in fams
            }
            embed_dim += cfg.hidden_dim * len(fams)
            hidden_dim += cfg.hidden_dim * len(fams)
        # factory hook: GGNNFused swaps in the Pallas VMEM-resident conv
        # under the same "ggnn" scope, keeping the parameter tree identical
        self.ggnn = self._conv(hidden_dim)
        out_in = embed_dim + hidden_dim
        if cfg.label_style == "graph":
            self.pooling = GlobalAttentionPooling(dtype=self.compute_dtype)
        if not cfg.encoder_mode:
            self.head = [
                nn.Dense(
                    1 if i == cfg.num_output_layers - 1 else out_in,
                    dtype=self.compute_dtype,
                    name=f"out_{i}",
                )
                for i in range(cfg.num_output_layers)
            ]

    def _conv(self, hidden_dim: int) -> nn.Module:
        """Build the message-passing conv (overridden by ``GGNNFused``)."""
        return GatedGraphConv(
            out_feats=hidden_dim,
            n_steps=self.cfg.n_steps,
            aggregation=self.cfg.aggregation,
            dtype=self.compute_dtype,
        )

    def _embed_dfa(self, batch: BatchedGraphs) -> jnp.ndarray:
        # same fused-gather trick as the subkey tables: the family tables
        # differ in row count but share the hidden width, so they stack along
        # axis 0 with cumulative row offsets into the ids.
        fams = active_dfa_families(self.cfg.dataflow_families,
                                   self.cfg.interproc_families)
        table = jnp.concatenate(
            [self.dfa_embeddings[fam].embedding for fam in fams], axis=0
        ).astype(self.compute_dtype)
        ids_cols = []
        offset = 0
        for fam in fams:
            ids_cols.append(batch.node_feats[f"_DFA_{fam}"] + offset)
            offset += DFA_FEATURE_DIMS[fam]
        ids = jnp.stack(ids_cols, axis=-1)
        out = jnp.take(table, ids, axis=0)
        return out.reshape(*ids.shape[:-1], -1)

    def embed_nodes(self, batch: BatchedGraphs) -> jnp.ndarray:
        if self.cfg.concat_all_absdf:
            # One fused gather instead of 4: stack the per-subkey tables into
            # a (4·input_dim, embed) matrix (params-only concat — XLA hoists
            # it out of the step), offset each subkey's ids into its table
            # slice, gather once, and flatten (n, 4, embed) -> (n, 4·embed).
            # Row-major reshape preserves exactly the per-subkey concat order.
            table = jnp.concatenate(
                [self.embeddings[sk].embedding for sk in ALL_SUBKEYS], axis=0
            ).astype(self.compute_dtype)
            ids = jnp.stack(
                [
                    batch.node_feats[f"_ABS_DATAFLOW_{sk}"] + i * self.input_dim
                    for i, sk in enumerate(ALL_SUBKEYS)
                ],
                axis=-1,
            )
            out = jnp.take(table, ids, axis=0)
            out = out.reshape(*ids.shape[:-1], -1)
        else:
            out = self.embedding(batch.node_feats["_ABS_DATAFLOW"])
        if self.cfg.dataflow_families or self.cfg.interproc_families:
            out = jnp.concatenate([out, self._embed_dfa(batch)], axis=-1)
        return out

    def __call__(self, batch: BatchedGraphs, taps: tuple | None = None) -> jnp.ndarray:
        cfg = self.cfg
        feat_embed = self.embed_nodes(batch)
        ggnn_out = self.ggnn(feat_embed, batch.senders, batch.receivers, taps=taps)
        out = jnp.concatenate([ggnn_out, feat_embed], axis=-1)
        if cfg.label_style == "graph":
            out = self.pooling(
                out, batch.node_gidx, batch.node_mask, batch.max_graphs
            )
        if cfg.encoder_mode:
            return out
        for i, layer in enumerate(self.head):
            out = layer(out)
            if i != len(self.head) - 1:
                out = nn.relu(out)
        return out[..., 0].astype(jnp.float32)
