"""Flax models: GGNN encoder/classifier, fusion heads, Llama-family LLM."""

from deepdfa_tpu.config import GGNNConfig

__all__ = ["make_model"]


def make_model(cfg: GGNNConfig, input_dim: int):
    """The flagship model in the configured graph layout. All layouts share
    one parameter tree (parity-tested), so a checkpoint trained in any
    restores into the others."""
    if cfg.layout == "dense":
        from deepdfa_tpu.models.ggnn_dense import GGNNDense

        return GGNNDense(cfg=cfg, input_dim=input_dim)
    if cfg.layout == "fused":
        from deepdfa_tpu.models.ggnn_fused import GGNNFused

        return GGNNFused(cfg=cfg, input_dim=input_dim)
    if cfg.layout == "megabatch":
        from deepdfa_tpu.models.ggnn_megabatch import GGNNMegabatch

        return GGNNMegabatch(cfg=cfg, input_dim=input_dim)
    if cfg.layout != "segment":
        raise ValueError(
            f"unknown layout {cfg.layout!r} (segment | dense | fused | "
            "megabatch)"
        )
    from deepdfa_tpu.models.ggnn import GGNN

    return GGNN(cfg=cfg, input_dim=input_dim)
