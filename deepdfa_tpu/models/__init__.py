"""Flax models: GGNN encoder/classifier, fusion heads, Llama-family LLM."""
