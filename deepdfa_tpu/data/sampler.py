"""Per-epoch class rebalancing, host-side.

Parity with the reference's epoch resampling: ``BigVulDataset.get_epoch_indices``
(``DDFA/sastvd/helpers/dclass.py:84-105``) driven by
``reload_dataloaders_every_n_epochs: 1`` (``config_default.yaml``) — each epoch
re-draws the non-vulnerable subset and reshuffles. The ``"vX"`` undersample
syntax keeps ``X × n_vul`` non-vul examples; a plain float keeps that fraction
of all non-vul; ``oversample`` duplicates vul examples with replacement.

The output is an *ordering of graph indices*; the fixed-shape
``GraphBatcher`` consumes it, so dynamic sampling composes with static XLA
shapes (SURVEY.md §7 "hard parts").
"""

from __future__ import annotations

import numpy as np

__all__ = ["epoch_indices", "positive_weight"]


def epoch_indices(
    labels: np.ndarray,
    undersample: str | float | None = "v1.0",
    oversample: float | None = None,
    seed: int = 0,
    epoch: int = 0,
    shuffle: bool = True,
) -> np.ndarray:
    """Return the example indices to visit this epoch.

    ``labels``: per-example {0,1} vulnerability labels.
    """
    rng = np.random.default_rng(np.random.SeedSequence([seed, epoch]))
    idx = np.arange(len(labels))
    vul = idx[labels == 1]
    nonvul = idx[labels == 0]
    if undersample is not None:
        if isinstance(undersample, str) and undersample.startswith("v"):
            k = int(len(vul) * float(undersample[1:]))
        else:
            k = int(len(nonvul) * float(undersample))
        k = min(k, len(nonvul))
        nonvul = rng.choice(nonvul, size=k, replace=False)
    if oversample is not None:
        vul = rng.choice(vul, size=int(len(vul) * oversample), replace=True)
    out = np.concatenate([vul, nonvul])
    if shuffle:
        rng.shuffle(out)
    return out


def positive_weight(labels: np.ndarray) -> float:
    """``n_neg / n_pos`` over the train set, the BCE pos_weight
    (``linevd/datamodule.py:98-108``)."""
    n_pos = int((labels == 1).sum())
    n_neg = int(len(labels) - n_pos)
    return n_neg / max(n_pos, 1)
