"""Train-split abstract-dataflow vocabularies and node-feature encoding.

Re-design of the reference's ``abs_dataflow`` (``helpers/datasets.py:587-692``)
and the ``nodes_feat_*`` grid writer (``sastvd/scripts/dbize_absdf.py``):

- per-subkey vocabularies are frequency-ranked over **train-split
  definitions only** with a ``limit_subkeys`` cutoff; index 0 is reserved
  (``hashes.insert(0, None)``, ``datasets.py:641-644``);
- the combined vocabulary re-hashes each definition with out-of-vocab subkey
  values replaced by ``"UNKNOWN"`` (unless ``include_unknown``), then ranks
  the combined JSON hashes with a ``limit_all`` cutoff (``:648-688``);
- node feature ids follow ``dbize_absdf.py:34-43``: ``0`` = not a
  definition, ``1`` = definition with out-of-vocab hash (UNKNOWN), ``2..``
  = known hashes — hence ``input_dim = limit_all + 2``
  (``linevd/datamodule.py:87-96``).

Known deliberate deviation: the reference computes ``hash.all`` through a
train-frame ``apply`` whose result is assigned back by *positional* index
(``datasets.py:674-675``), leaving non-train rows' combined hashes
misaligned; we compute every row's combined hash directly (the evident
intent — vocab ranking still uses train rows only).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Iterable, Mapping

import pandas as pd

from deepdfa_tpu.config import ALL_SUBKEYS, SINGLE_SUBKEYS, FeatureConfig

__all__ = ["Vocabulary", "build_vocab", "encode_nodes", "encode_dfa_nodes", "UNKNOWN"]

UNKNOWN = "UNKNOWN"


def _hash_values(hash_dict: Mapping[str, list], subkey: str) -> list[str]:
    """The (deduped, sorted) subkey values of one definition hash; datatype
    is single-valued (``datasets.py:551-556,620-627``)."""
    values = [str(v) for v in hash_dict.get(subkey, [])]
    if SINGLE_SUBKEYS.get(subkey, False):
        return values[:1]
    return sorted(set(values))


@dataclasses.dataclass(frozen=True)
class Vocabulary:
    """Subkey vocabs + the combined vocab for one :class:`FeatureConfig`."""

    cfg: FeatureConfig
    subkey_vocabs: dict[str, dict[str, int]]
    all_vocab: dict[str | None, int]

    def combined_hash(self, hash_dict: Mapping[str, list]) -> str:
        """Canonical combined hash with UNKNOWN substitution
        (``datasets.py:649-672``)."""
        out = {}
        for sk in sorted(self.cfg.subkeys):
            values = _hash_values(hash_dict, sk)
            if not self.cfg.include_unknown:
                vocab = self.subkey_vocabs[sk]
                values = [v if v in vocab else UNKNOWN for v in values]
            out[sk] = sorted(set(values))
        return json.dumps(out)

    def feature_id(self, hash_json: str | None) -> int:
        """Node feature id: 0 not-a-def, 1 UNKNOWN, 2.. known
        (``dbize_absdf.py:34-43``)."""
        if hash_json is None:
            return 0
        return self.feature_id_from_dict(json.loads(hash_json))

    def feature_id_from_dict(self, hash_dict: Mapping[str, list]) -> int:
        """:meth:`feature_id` for an already-parsed hash (bulk callers —
        the coverage grid — parse each hash once, not once per variant)."""
        combined = self.combined_hash(hash_dict)
        return self.all_vocab.get(combined, 0) + 1

    @property
    def input_dim(self) -> int:
        return self.cfg.input_dim

    def to_dict(self) -> dict:
        """Full JSON-serialisable form. ``all_vocab`` alone (what the shard
        dir's ``vocab.json`` used to carry) cannot encode NEW code: with
        ``include_unknown=False`` (the reference default) the combined hash
        substitutes UNKNOWN for out-of-vocab subkey values, which needs
        ``subkey_vocabs`` — the serialisation predict-time encoding loads."""
        return {
            "cfg": dataclasses.asdict(self.cfg),
            "subkey_vocabs": self.subkey_vocabs,
            "all_vocab": self.all_vocab,
        }

    @classmethod
    def from_dict(cls, d: Mapping) -> "Vocabulary":
        cfg_d = dict(d["cfg"])
        cfg_d["subkeys"] = tuple(cfg_d["subkeys"])
        return cls(
            cfg=FeatureConfig(**cfg_d),
            subkey_vocabs={k: dict(v) for k, v in d["subkey_vocabs"].items()},
            all_vocab={k: int(v) for k, v in d["all_vocab"].items()},
        )


def _rank(values: pd.Series, limit: int | None) -> dict:
    counts = values.value_counts()
    if limit is not None:
        counts = counts.head(limit)
    return {v: i + 1 for i, v in enumerate(counts.index)}


def build_vocab(
    hash_df: pd.DataFrame, train_ids: Iterable[int], cfg: FeatureConfig
) -> Vocabulary:
    """Build vocabularies from stage-2 hashes.

    ``hash_df``: columns ``graph_id, node_id, hash`` (JSON). Ranking uses
    only rows whose ``graph_id`` is in ``train_ids`` — train-split-only
    vocab determinism is a correctness requirement (SURVEY.md §7).
    """
    train_ids = set(int(i) for i in train_ids)
    df = hash_df.copy()
    if "hash_dict" not in df.columns:  # bulk callers may pre-parse once
        df["hash_dict"] = df["hash"].apply(json.loads)
    train = df[df.graph_id.isin(train_ids)]

    subkey_vocabs: dict[str, dict[str, int]] = {}
    for sk in cfg.subkeys:
        exploded = train["hash_dict"].apply(lambda h: _hash_values(h, sk)).explode().dropna()
        subkey_vocabs[sk] = _rank(exploded, cfg.limit_subkeys)

    vocab = Vocabulary(cfg=cfg, subkey_vocabs=subkey_vocabs, all_vocab={})
    combined_train = train["hash_dict"].apply(vocab.combined_hash)
    all_vocab = _rank(combined_train, cfg.limit_all)
    return dataclasses.replace(vocab, all_vocab=all_vocab)


def encode_nodes(
    node_ids: Iterable[int],
    graph_hashes: Mapping[int, str],
    vocab: Vocabulary,
) -> list[int]:
    """Feature ids for one graph's nodes. ``graph_hashes`` maps node_id →
    stage-2 hash JSON for that graph's definitions; non-definition nodes
    get 0."""
    return [vocab.feature_id(graph_hashes.get(int(n))) for n in node_ids]


def encode_dfa_nodes(
    node_ids: Iterable[int], family_values: Mapping[int, int], family: str
) -> list[int]:
    """Feature ids for one static-analysis family (``config.DFA_FAMILIES``).

    These families have small closed value sets instead of learned vocabs,
    so encoding is just clipping into the family's embedding-table range
    (``DFA_FEATURE_DIMS``); nodes the analysis didn't touch get 0.
    """
    from deepdfa_tpu.config import DFA_FEATURE_DIMS

    dim = DFA_FEATURE_DIMS[family]
    return [min(max(int(family_values.get(int(n), 0)), 0), dim - 1) for n in node_ids]
