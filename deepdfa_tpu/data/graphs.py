"""Fixed-shape batched graph container and host-side batcher.

Replaces the reference's DGL graph batching (``dgl.batch`` collate in
``GraphDataLoader``, ``linevd/datamodule.py:110-141``, and the ``graphs.bin``
serialization of ``sastvd/scripts/dbize_graphs.py:20-33``) with an
XLA-friendly design:

- :class:`BatchedGraphs` — flat arrays with **static shapes**: every batch in a
  bucket has exactly ``max_nodes`` nodes, ``max_edges`` edges and
  ``max_graphs`` graph slots; real entries are marked by masks.
- Padding convention: the **last graph slot(s)** own all padding nodes; padding
  edges are self-loops on the last (padding) node. Segment reductions therefore
  dump padding contributions into padding slots that masks exclude — no
  device-side filtering needed.
- :func:`batch_np` — host-side (numpy) packer: concatenate graphs with node
  offsets, then pad to the bucket budget.
- :class:`GraphBatcher` — greedy packer over a dataset producing fixed-shape
  batches under (graphs, nodes, edges) budgets, with optional multi-bucket
  support to bound padding waste at a bounded number of XLA compilations.

Serialization: ``save_shards``/``load_shards`` store per-graph arrays in
``.npz`` shards (replacing DGL's ``graphs.bin``).
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, NamedTuple, Sequence

import numpy as np

__all__ = [
    "Graph",
    "BatchedGraphs",
    "batch_np",
    "GraphBatcher",
    "BucketSpec",
    "derive_buckets",
    "padding_efficiency",
    "save_shards",
    "load_shards",
    "ShardIntegrityError",
]


@dataclasses.dataclass
class Graph:
    """A single (host-side, numpy) graph.

    ``node_feats`` values are ``[n_nodes, ...]`` arrays; integer feature ids,
    labels (``_VULN``), dataflow bit-vectors etc. all live here. The dict is
    carried generically through batching/sharding — new feature families
    (e.g. the ``_DFA_{live_out,uninit,taint}`` static-analysis ids emitted
    when ``FeatureConfig.dataflow_families`` is on) need no carrier changes.
    """

    senders: np.ndarray  # [n_edges] int32, source node index
    receivers: np.ndarray  # [n_edges] int32
    node_feats: dict[str, np.ndarray]
    gid: int = -1  # dataset graph id (Big-Vul function id); host-side only

    @property
    def n_nodes(self) -> int:
        for v in self.node_feats.values():
            return int(v.shape[0])
        if self.senders.size == 0:
            return 0
        return int(max(self.senders.max(), self.receivers.max()) + 1)

    @property
    def n_edges(self) -> int:
        return int(self.senders.shape[0])

    def with_self_loops(self) -> "Graph":
        """Append one self-loop per node (parity with ``dbize_graphs.py:26``,
        which calls ``dgl.add_self_loop``); required by GGNN message passing so
        every node sees its own state."""
        n = self.n_nodes
        loop = np.arange(n, dtype=np.int32)
        return dataclasses.replace(
            self,
            senders=np.concatenate([self.senders.astype(np.int32), loop]),
            receivers=np.concatenate([self.receivers.astype(np.int32), loop]),
        )


class BatchedGraphs(NamedTuple):
    """Device-ready batch. All shapes static within a bucket.

    node_feats: dict of ``[max_nodes, ...]`` arrays.
    senders/receivers: ``[max_edges]`` int32 into the node axis, SORTED by
    receiver (``batch_np`` contract) so segment reductions over receivers
    may pass ``indices_are_sorted=True``.
    node_gidx: ``[max_nodes]`` int32 graph slot of each node.
    node_mask / edge_mask / graph_mask: bool validity masks.
    """

    node_feats: dict
    senders: np.ndarray
    receivers: np.ndarray
    node_gidx: np.ndarray
    node_mask: np.ndarray
    edge_mask: np.ndarray
    graph_mask: np.ndarray

    @property
    def max_nodes(self) -> int:
        return self.node_gidx.shape[0]

    @property
    def max_graphs(self) -> int:
        return self.graph_mask.shape[0]


def batch_np(
    graphs: Sequence[Graph],
    max_graphs: int,
    max_nodes: int,
    max_edges: int,
    extra_feat_pad: dict[str, float] | None = None,
) -> BatchedGraphs:
    """Concatenate ``graphs`` and pad to the static budget (numpy, host-side).

    Requires ``sum(n_nodes) <= max_nodes - 1`` (one node reserved for edge
    padding) and ``len(graphs) <= max_graphs - 1`` (one slot reserved as the
    padding graph).
    """
    n_real = len(graphs)
    tot_nodes = sum(g.n_nodes for g in graphs)
    tot_edges = sum(g.n_edges for g in graphs)
    if n_real > max_graphs - 1:
        raise ValueError(f"{n_real} graphs > budget {max_graphs - 1}")
    if tot_nodes > max_nodes - 1:
        raise ValueError(f"{tot_nodes} nodes > budget {max_nodes - 1}")
    if tot_edges > max_edges:
        raise ValueError(f"{tot_edges} edges > budget {max_edges}")

    senders = np.full(max_edges, max_nodes - 1, dtype=np.int32)
    receivers = np.full(max_edges, max_nodes - 1, dtype=np.int32)
    node_gidx = np.full(max_nodes, max_graphs - 1, dtype=np.int32)

    node_off = 0
    edge_off = 0
    for gi, g in enumerate(graphs):
        nn, ne = g.n_nodes, g.n_edges
        senders[edge_off : edge_off + ne] = g.senders + node_off
        receivers[edge_off : edge_off + ne] = g.receivers + node_off
        node_gidx[node_off : node_off + nn] = gi
        node_off += nn
        edge_off += ne

    # Contract: edges sorted by receiver (stable). Real receivers are all
    # < max_nodes-1 (the padding sink), so padding edges stay at the end.
    # Sorting here — cheap numpy on the host, once per batch — lets every
    # device-side scatter-add take XLA's sorted-segment fast path, and the
    # model no longer pays a device-side O(E log² E) bitonic argsort once
    # per jitted forward.
    order = np.argsort(receivers, kind="stable")
    senders = senders[order]
    receivers = receivers[order]

    node_feats: dict[str, np.ndarray] = {}
    keys = graphs[0].node_feats.keys() if graphs else ()
    pad_values = extra_feat_pad or {}
    for key in keys:
        parts = [g.node_feats[key] for g in graphs]
        sample = parts[0]
        shape = (max_nodes,) + sample.shape[1:]
        out = np.full(shape, pad_values.get(key, 0), dtype=sample.dtype)
        cat = np.concatenate(parts, axis=0)
        out[: cat.shape[0]] = cat
        node_feats[key] = out

    node_mask = np.arange(max_nodes) < tot_nodes
    edge_mask = np.arange(max_edges) < tot_edges
    graph_mask = np.arange(max_graphs) < n_real
    return BatchedGraphs(
        node_feats=node_feats,
        senders=senders,
        receivers=receivers,
        node_gidx=node_gidx,
        node_mask=node_mask,
        edge_mask=edge_mask,
        graph_mask=graph_mask,
    )


@dataclasses.dataclass(frozen=True)
class BucketSpec:
    """Static padded-batch budget. One graph slot (``max_graphs - 1``) and one
    node slot (``max_nodes - 1``) are RESERVED as the padding sinks — padding
    nodes point at the sink graph, padding edges at the sink node — so a
    bucket holds at most ``max_graphs - 1`` real graphs over
    ``max_nodes - 1`` real nodes (see :func:`batch_np`). ``max_graphs`` and
    ``max_nodes`` must therefore be ≥ 2 for the bucket to hold anything."""

    max_graphs: int
    max_nodes: int
    max_edges: int

    def fits(self, n_graphs: int, n_nodes: int, n_edges: int) -> bool:
        return (
            n_graphs <= self.max_graphs - 1
            and n_nodes <= self.max_nodes - 1
            and n_edges <= self.max_edges
        )


class GraphBatcher:
    """Greedy fixed-shape packer.

    Packs graphs in the given order until the next graph would exceed the
    bucket budget, then emits a padded :class:`BatchedGraphs`. With multiple
    buckets, each emitted batch uses the smallest bucket that fits, bounding
    both padding waste and the number of distinct compiled shapes.

    This is the XLA replacement for per-epoch dynamic ``dgl.batch`` collate;
    per-epoch undersampling composes with it by re-ordering/re-selecting the
    graph list host-side each epoch (see ``data/sampler.py``).
    """

    def __init__(self, buckets: Sequence[BucketSpec], drop_oversize: bool = True,
                 collect_oversize: bool = False):
        if not buckets:
            raise ValueError("need at least one bucket")
        for b in buckets:
            if b.max_graphs < 2 or b.max_nodes < 2:
                # the padding-sink reservation makes such a bucket hold zero
                # real graphs — with drop_oversize it would silently drop ALL
                raise ValueError(
                    f"unusable bucket {b}: max_graphs and max_nodes must be "
                    "≥ 2 (one slot each is reserved as the padding sink)"
                )
        self.buckets = sorted(buckets, key=lambda b: (b.max_nodes, b.max_edges, b.max_graphs))
        self.big = self.buckets[-1]
        self.drop_oversize = drop_oversize
        self.collect_oversize = collect_oversize
        self.n_dropped = 0
        self.oversize_graphs: list[Graph] = []

    def batches(self, graphs: Sequence[Graph]) -> Iterator[BatchedGraphs]:
        # per-pass counters (batches() is re-run every epoch)
        self.n_dropped = 0
        self.oversize_graphs = []
        pending: list[Graph] = []
        nn = ne = 0
        for g in graphs:
            if not self.big.fits(1, g.n_nodes, g.n_edges):
                if self.collect_oversize:
                    # kept for the caller to rescue through a dedicated
                    # overflow bucket (trainer route) — nothing silently lost
                    self.oversize_graphs.append(g)
                    continue
                if self.drop_oversize:
                    self.n_dropped += 1
                    continue
                raise ValueError(
                    f"graph gid={g.gid} ({g.n_nodes} nodes, {g.n_edges} edges) "
                    f"exceeds the largest bucket {self.big}"
                )
            if pending and not self.big.fits(len(pending) + 1, nn + g.n_nodes, ne + g.n_edges):
                yield self._emit(pending, nn, ne)
                pending, nn, ne = [], 0, 0
            pending.append(g)
            nn += g.n_nodes
            ne += g.n_edges
        if pending:
            yield self._emit(pending, nn, ne)

    def _emit(self, pending: list[Graph], nn: int, ne: int) -> BatchedGraphs:
        bucket = next(b for b in self.buckets if b.fits(len(pending), nn, ne))
        return batch_np(pending, bucket.max_graphs, bucket.max_nodes, bucket.max_edges)


def _round_up(x: int, mult: int = 128) -> int:
    return ((int(x) + mult - 1) // mult) * mult


def derive_buckets(
    graphs: Sequence[Graph],
    batch_graphs: int,
    headroom: float = 1.08,
    sub_buckets: Sequence[float] = (0.25, 0.5),
    round_to: int = 128,
) -> list[BucketSpec]:
    """Bucket budgets sized to the corpus instead of a worst-case constant.

    The reference's DGL collate pays no padding (ragged batches); a static-
    shape TPU batch does, so budgets matter: a 40,960-node budget holding
    ~15k real nodes runs the dense GGNN matmuls ~3× oversized. This derives
    the main bucket from measured mean nodes/edges per graph
    (``batch_graphs × mean × headroom``, rounded up to ``round_to`` for MXU-
    friendly tiling) plus scaled-down sub-buckets so tail batches (end of
    epoch, node-budget-limited packs) don't pay full-size padding either.
    """
    if not graphs:
        raise ValueError("cannot derive buckets from an empty corpus")
    mean_nodes = float(np.mean([g.n_nodes for g in graphs]))
    mean_edges = float(np.mean([g.n_edges for g in graphs]))
    max_nodes_1 = max(g.n_nodes for g in graphs)
    max_edges_1 = max(g.n_edges for g in graphs)

    def spec(frac: float) -> BucketSpec:
        n_g = max(int(round(batch_graphs * frac)), 1)
        return BucketSpec(
            max_graphs=n_g + 1,
            # a bucket must hold at least the largest single graph
            max_nodes=_round_up(max(n_g * mean_nodes * headroom, max_nodes_1 + 1), round_to),
            max_edges=_round_up(max(n_g * mean_edges * headroom, max_edges_1), round_to),
        )

    buckets = [spec(f) for f in (*sub_buckets, 1.0)]
    # drop sub-buckets that collapsed into the same size as a larger one
    out: list[BucketSpec] = []
    for b in buckets:
        if not out or b != out[-1]:
            out.append(b)
    return out


def padding_efficiency(batches: Sequence[BatchedGraphs]) -> dict[str, float]:
    """Fraction of the padded budgets occupied by real entries. The node
    number is the direct multiplier on useful FLOPs in the dense GGNN path."""
    real_n = sum(int(b.node_mask.sum()) for b in batches)
    real_e = sum(int(b.edge_mask.sum()) for b in batches)
    real_g = sum(int(b.graph_mask.sum()) for b in batches)
    pad_n = sum(b.node_mask.shape[0] for b in batches)
    pad_e = sum(b.edge_mask.shape[0] for b in batches)
    pad_g = sum(b.graph_mask.shape[0] for b in batches)
    return {
        "nodes": real_n / pad_n if pad_n else 0.0,
        "edges": real_e / pad_e if pad_e else 0.0,
        "graphs": real_g / pad_g if pad_g else 0.0,
    }


class ShardIntegrityError(RuntimeError):
    """A materialised shard failed its sha256 manifest check — names the
    corrupt shard so the operator can re-materialise it, instead of a
    downstream npz/pickle decode crash pointing nowhere."""


def _sha256_file(path) -> str:
    import hashlib

    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def save_shards(graphs: Sequence[Graph], out_dir, shard_size: int = 4096) -> int:
    """Write graphs to ``shard_{i:05d}.npz`` files (replaces ``graphs.bin``)
    plus a ``manifest.json`` recording each shard's sha256 + graph count —
    :func:`load_shards` verifies the hashes before decoding anything."""
    import json
    from pathlib import Path

    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    n_shards = 0
    manifest: dict[str, dict] = {}
    for si in range(0, len(graphs), shard_size):
        chunk = graphs[si : si + shard_size]
        payload: dict[str, np.ndarray] = {
            "gids": np.array([g.gid for g in chunk], dtype=np.int64)
        }
        for i, g in enumerate(chunk):
            payload[f"s{i}"] = g.senders.astype(np.int32)
            payload[f"r{i}"] = g.receivers.astype(np.int32)
            for key, val in g.node_feats.items():
                payload[f"f{i}:{key}"] = val
        name = f"shard_{n_shards:05d}.npz"
        np.savez_compressed(out / name, **payload)
        manifest[name] = {"sha256": _sha256_file(out / name), "graphs": len(chunk)}
        n_shards += 1
    # atomic sidecar (journal protocol): a crash mid-write must not leave a
    # torn manifest that poisons every future load
    from deepdfa_tpu.resilience.journal import atomic_write_text

    atomic_write_text(
        out / "manifest.json",
        json.dumps({"schema": 1, "shards": manifest}, indent=2),
    )
    return n_shards


def load_shards(in_dir) -> list[Graph]:
    """Load materialised shards; when a ``manifest.json`` is present (every
    corpus written since the manifest landed) each shard's sha256 is
    verified FIRST — a flipped bit or truncated file raises
    :class:`ShardIntegrityError` naming the corrupt shard. Legacy dirs
    without a manifest load unverified."""
    import json
    import logging
    from pathlib import Path

    shard_files = sorted(Path(in_dir).glob("shard_*.npz"))
    manifest_file = Path(in_dir) / "manifest.json"
    if manifest_file.exists():
        entries = json.loads(manifest_file.read_text()).get("shards", {})
        on_disk = {p.name for p in shard_files}
        missing = sorted(set(entries) - on_disk)
        if missing:
            raise ShardIntegrityError(
                f"shard(s) listed in {manifest_file} but missing on disk: "
                f"{', '.join(missing)}"
            )
        for shard in shard_files:
            entry = entries.get(shard.name)
            if entry is None:
                raise ShardIntegrityError(
                    f"shard {shard.name} present on disk but not in "
                    f"{manifest_file} — stale or foreign file in the shard dir"
                )
            digest = _sha256_file(shard)
            if digest != entry["sha256"]:
                logging.getLogger(__name__).error(
                    "shard integrity failure: %s sha256 %s != recorded %s",
                    shard, digest, entry["sha256"],
                )
                raise ShardIntegrityError(
                    f"shard {shard.name} is corrupt: sha256 {digest[:12]}… does "
                    f"not match the manifest ({entry['sha256'][:12]}…) — "
                    "re-materialise the corpus"
                )

    graphs: list[Graph] = []
    for shard in shard_files:
        with np.load(shard) as z:
            gids = z["gids"]
            for i, gid in enumerate(gids):
                feats = {
                    k.split(":", 1)[1]: z[k]
                    for k in z.files
                    if k.startswith(f"f{i}:")
                }
                graphs.append(
                    Graph(
                        senders=z[f"s{i}"],
                        receivers=z[f"r{i}"],
                        node_feats=feats,
                        gid=int(gid),
                    )
                )
    return graphs
