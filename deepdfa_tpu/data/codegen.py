"""Synthetic C source corpus with known vulnerable lines.

The reference's test fixture is a 200-function sample of the downloaded
Big-Vul CSV (``sastvd/scripts/sample_MSR_data.py``); this environment has no
network, so the hermetic analogue is *generated* C: template-based functions
where the vulnerable variants contain a classic memory-safety defect on a
known line (unbounded ``strcpy``/``memcpy``/index write), and the fixed
variants bound it. Unlike :mod:`deepdfa_tpu.data.synthetic` (random graphs),
this feeds the REAL pipeline — native frontend → reaching-defs → abstract
dataflow → vocab → shards — so end-to-end runs exercise every stage on
actual source text.

Output schema matches the ingestion contract (``ingest.bigvul``): columns
``id, before, after, vul, removed, added``.
"""

from __future__ import annotations

import numpy as np
import pandas as pd

__all__ = ["generate_function", "generate_hard_function", "demo_corpus"]


def _names(rng: np.random.Generator, n: int) -> list[str]:
    pool = ["acc", "buf", "cnt", "idx", "len", "out", "ptr", "sum", "tmp", "val"]
    picks = rng.choice(len(pool), size=n, replace=False)
    return [pool[i] + str(int(rng.integers(0, 100))) for i in picks]


def generate_function(fid: int, vul: bool, rng: np.random.Generator) -> dict:
    """One (before, after) pair. Vulnerable: the ``before`` body copies into a
    fixed buffer without a bound; the ``after`` adds the bound — so ``removed``
    (the vul lines) and ``added`` mirror a real security patch's diff."""
    a, b, c = _names(rng, 3)
    k1, k2 = int(rng.integers(1, 9)), int(rng.integers(16, 64))
    filler_pool = [
        f"    int {a} = {c}[0] + {k1};",
        f"    int {b} = {a} * {k1};",
        f"    if ({a} > {k1}) {{ {b} = {a} - 1; }}",
        f"    for (int i = 0; i < {k1}; i++) {{ {b} += i; }}",
    ]
    n_filler = int(rng.integers(1, len(filler_pool) + 1))
    filler = [filler_pool[i] for i in sorted(rng.choice(len(filler_pool), n_filler, replace=False))]

    head = f"int f{fid}(char *{c}, int n)"
    # The defect must be visible to *abstract dataflow*: features come from
    # definitions only (assignments), so the vulnerable copy bound is an
    # unchecked strlen-derived def, the fixed one a clamped arithmetic def —
    # distinct (api, operator) subkeys, like real taint-vs-sanitized code.
    vul_lines = [
        f"    int cap{fid} = strlen({c});",
        f"    memcpy(dst{fid}, {c}, cap{fid});",
    ]
    safe_lines = [
        f"    int cap{fid} = (n < {k2}) ? n : {k2} - 1;",
        f"    memcpy(dst{fid}, {c}, cap{fid});",
    ]
    decl = f"    char dst{fid}[{k2}];"

    def render(mid: list[str]) -> str:
        return "\n".join([head, "{", decl, *filler, *mid, f"    return n + {k1};", "}"])

    before = render(vul_lines if vul else safe_lines)
    after = render(safe_lines)
    if vul:
        # the unchecked-bound def line in `before` (1-based: header, "{",
        # decl, fillers, then the strlen def)
        removed = [3 + len(filler) + 1]
        added = [3 + len(filler) + 1]  # the clamped def replaces it in `after`
    else:
        removed, added = [], []
    return {
        "id": fid,
        "before": before,
        "after": after,
        "vul": int(vul),
        "removed": removed,
        "added": added,
    }


def generate_hard_function(
    fid: int, vul: bool, rng: np.random.Generator, chain_depth: int | None = None
) -> dict:
    """A *dataflow-hard* (before, after) pair: both classes are built from the
    SAME statement multiset — identical per-node abstract-dataflow features,
    identical token histogram — and differ ONLY in the CFG order of two
    statements:

        T:  ``cap = strlen(src);``              (tainted bound)
        C:  ``if (cap >= K) { cap = K - 1; }``  (clamp)

    safe order ``T;C``  → the clamp dominates the copy: IN(memcpy) ∋ clamp def
    vul order  ``C;T``  → the taint re-defines cap after the clamp:
                          IN(memcpy) = {taint def} only

    So the class is a function of *which definition reaches the copy* — pure
    reaching-definitions reasoning (the reference's learned-DFA thesis,
    ``clipper.py:50-77``); any bag-of-features classifier is at chance by
    construction. A random 0-8 statement gap between the clamp/taint block
    and the copy stretches the def→use chains past a fixed message-passing
    depth for some functions, keeping the task nontrivial for the GGNN too.

    The patch (``after``) restores the safe order, so ``removed``/``added``
    line labels mirror a real reordering fix.

    ``chain_depth=L`` switches to the **depth-controlled** variant (the
    union-vs-sum separation corpus, round-3): the two defs are separated by
    exactly ``L`` branch-merge statements over unrelated variables, and the
    copy follows immediately after the second def. Around every statement the
    two classes are locally identical (same taint, same clamp, same gap
    multiset); telling WHICH def comes last — i.e. which one reaches the
    ``memcpy`` — requires integrating order information across ≥ L CFG hops.
    Each gap ``if`` is a reconvergent diamond, so defs re-arrive along
    multiple paths: a sum aggregator accumulates path-multiplicity counts
    while an idempotent union (a∪a=a, the RD lattice meet) does not — the
    regime where the reference's differentiable-DFA aggregator
    (``clipper.py:50-77``) should earn its keep.
    """
    a, b, c = _names(rng, 3)
    k1 = int(rng.integers(2, 9))
    k2 = int(rng.integers(16, 64))
    cap = f"cap{fid}"

    taint = f"    {cap} = (int)strlen({c});"
    clamp = f"    if ({cap} >= {k2}) {{ {cap} = {k2} - 1; }}"

    if chain_depth is None:
        gap_pool = [
            f"    int {a} = {k1};",
            f"    int {b} = {a} + {k1};" if rng.random() < 0.5 else f"    int {b} = {k1} * 2;",
            f"    if ({a} > {k1}) {{ {a} = {a} - 1; }}",
            f"    for (int i = 0; i < {k1}; i++) {{ {b} += i; }}",
            f"    {b} = {b} ^ {a};",
            f"    while ({a} > 0) {{ {a} -= 1; }}",
            f"    {a} = {a} + {b};",
            f"    if ({b} > {a}) {{ {b} = {a}; }}",
        ]
        n_gap = int(rng.integers(0, 9))
        gap = [gap_pool[i] for i in sorted(rng.choice(len(gap_pool), min(n_gap, len(gap_pool)), replace=False))]
        between: list[str] = []
    else:
        # L branch-merge diamonds BETWEEN the defs; nothing after the second
        # def, so receptive-field distance to the copy is exactly the chain.
        between = [
            f"    if ({a} > {int(rng.integers(0, 99))}) {{ {b} = {b} + {i}; }}"
            for i in range(chain_depth)
        ]
        gap = []

    head = f"int f{fid}(char *{c}, int n)"
    decl = [f"    char dst{fid}[{k2}];", f"    int {cap} = 0;",
            f"    int {a} = n; int {b} = {k1};"] if chain_depth is not None else [
            f"    char dst{fid}[{k2}];", f"    int {cap} = 0;"]
    copy = f"    memcpy(dst{fid}, {c}, {cap});"
    tail = f"    return {cap};"

    def render(first: str, second: str) -> str:
        return "\n".join(
            [head, "{", *decl, first, *between, second, *gap, copy, tail, "}"]
        )

    before = render(clamp, taint) if vul else render(taint, clamp)
    after = render(taint, clamp)
    n_decl = len(decl)
    if vul:
        # 1-based: head, "{", decls, first def, between..., second def (taint)
        taint_line_before = 2 + n_decl + 1 + len(between) + 1
        copy_line = taint_line_before + len(gap) + 1
        removed = [taint_line_before, copy_line]
        added = [2 + n_decl + 1]  # taint moved before the clamp in `after`
    else:
        removed, added = [], []
    return {
        "id": fid,
        "before": before,
        "after": after,
        "vul": int(vul),
        "removed": removed,
        "added": added,
    }


def demo_corpus(
    n: int = 200,
    vul_ratio: float = 0.5,
    seed: int = 0,
    style: str = "easy",
    chain_depth: int | None = None,
) -> pd.DataFrame:
    """Balanced-ish labeled corpus (the sample CSV analogue: 100 vul +
    100 non-vul in the reference's sample mode). ``style="hard"`` uses the
    dataflow-hard generator (identical feature histograms across classes);
    ``chain_depth=L`` additionally pins the def→def CFG distance (the
    union-vs-sum separation corpus, dataset name ``demo_order{L}`` —
    "order" as in the def→def distance parameter, NOT a depth benchmark:
    the graph label stays locally decidable near the sink, so the knob
    does not force L-hop reasoning; the node-level RD task is the depth
    probe of record)."""
    import functools

    rng = np.random.default_rng(seed)
    if chain_depth is not None:
        gen = functools.partial(generate_hard_function, chain_depth=chain_depth)
        dataset = f"demo_order{chain_depth}"
    elif style == "hard":
        gen, dataset = generate_hard_function, "demo_hard"
    else:
        gen, dataset = generate_function, "demo"
    rows = [gen(fid, bool(rng.random() < vul_ratio), rng) for fid in range(n)]
    df = pd.DataFrame(rows)
    df["dataset"] = dataset
    return df
