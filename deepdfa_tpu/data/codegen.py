"""Synthetic C source corpus with known vulnerable lines.

The reference's test fixture is a 200-function sample of the downloaded
Big-Vul CSV (``sastvd/scripts/sample_MSR_data.py``); this environment has no
network, so the hermetic analogue is *generated* C: template-based functions
where the vulnerable variants contain a classic memory-safety defect on a
known line (unbounded ``strcpy``/``memcpy``/index write), and the fixed
variants bound it. Unlike :mod:`deepdfa_tpu.data.synthetic` (random graphs),
this feeds the REAL pipeline — native frontend → reaching-defs → abstract
dataflow → vocab → shards — so end-to-end runs exercise every stage on
actual source text.

Output schema matches the ingestion contract (``ingest.bigvul``): columns
``id, before, after, vul, removed, added``.
"""

from __future__ import annotations

import numpy as np
import pandas as pd

__all__ = ["generate_function", "demo_corpus"]


def _names(rng: np.random.Generator, n: int) -> list[str]:
    pool = ["acc", "buf", "cnt", "idx", "len", "out", "ptr", "sum", "tmp", "val"]
    picks = rng.choice(len(pool), size=n, replace=False)
    return [pool[i] + str(int(rng.integers(0, 100))) for i in picks]


def generate_function(fid: int, vul: bool, rng: np.random.Generator) -> dict:
    """One (before, after) pair. Vulnerable: the ``before`` body copies into a
    fixed buffer without a bound; the ``after`` adds the bound — so ``removed``
    (the vul lines) and ``added`` mirror a real security patch's diff."""
    a, b, c = _names(rng, 3)
    k1, k2 = int(rng.integers(1, 9)), int(rng.integers(16, 64))
    filler_pool = [
        f"    int {a} = {c}[0] + {k1};",
        f"    int {b} = {a} * {k1};",
        f"    if ({a} > {k1}) {{ {b} = {a} - 1; }}",
        f"    for (int i = 0; i < {k1}; i++) {{ {b} += i; }}",
    ]
    n_filler = int(rng.integers(1, len(filler_pool) + 1))
    filler = [filler_pool[i] for i in sorted(rng.choice(len(filler_pool), n_filler, replace=False))]

    head = f"int f{fid}(char *{c}, int n)"
    # The defect must be visible to *abstract dataflow*: features come from
    # definitions only (assignments), so the vulnerable copy bound is an
    # unchecked strlen-derived def, the fixed one a clamped arithmetic def —
    # distinct (api, operator) subkeys, like real taint-vs-sanitized code.
    vul_lines = [
        f"    int cap{fid} = strlen({c});",
        f"    memcpy(dst{fid}, {c}, cap{fid});",
    ]
    safe_lines = [
        f"    int cap{fid} = (n < {k2}) ? n : {k2} - 1;",
        f"    memcpy(dst{fid}, {c}, cap{fid});",
    ]
    decl = f"    char dst{fid}[{k2}];"

    def render(mid: list[str]) -> str:
        return "\n".join([head, "{", decl, *filler, *mid, f"    return n + {k1};", "}"])

    before = render(vul_lines if vul else safe_lines)
    after = render(safe_lines)
    if vul:
        # the unchecked-bound def line in `before` (1-based: header, "{",
        # decl, fillers, then the strlen def)
        removed = [3 + len(filler) + 1]
        added = [3 + len(filler) + 1]  # the clamped def replaces it in `after`
    else:
        removed, added = [], []
    return {
        "id": fid,
        "before": before,
        "after": after,
        "vul": int(vul),
        "removed": removed,
        "added": added,
    }


def demo_corpus(n: int = 200, vul_ratio: float = 0.5, seed: int = 0) -> pd.DataFrame:
    """Balanced-ish labeled corpus (the sample CSV analogue: 100 vul +
    100 non-vul in the reference's sample mode)."""
    rng = np.random.default_rng(seed)
    rows = [
        generate_function(fid, bool(rng.random() < vul_ratio), rng)
        for fid in range(n)
    ]
    df = pd.DataFrame(rows)
    df["dataset"] = "demo"
    return df
