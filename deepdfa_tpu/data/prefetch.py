"""Host→device prefetch for training streams.

The reference feeds its trainer through DGL ``GraphDataLoader`` worker
processes (``linevd/datamodule.py:110-129``, ``train_workers`` — host-side
collation overlapped with GPU compute). The JAX-native equivalent is a
background thread that builds the next batches and stages them on device
(``jax.device_put``) while the current step runs: device dispatch is async,
so the only way the host stalls the chip is by not having the NEXT batch
ready — exactly what this removes.

On the tunneled single-chip setup the host→device copy rides the same
~70 ms-RTT link as everything else, which makes overlapping it with compute
matter MORE, not less, than on local PCIe.

Usage::

    for batch in prefetch_to_device(batch_iter, size=2):
        state, metrics, loss, _ = trainer.train_step(state, batch, metrics)

Exceptions raised by the producer (e.g. an oversize graph rejected by the
batcher mid-stream) are re-raised in the consumer at the point of ``next()``
— never swallowed in the thread.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Iterable, Iterator

from deepdfa_tpu.resilience import faults

__all__ = ["prefetch_to_device"]

_SENTINEL = object()


class _ProducerError:
    def __init__(self, exc: BaseException):
        self.exc = exc


def prefetch_to_device(
    iterator: Iterable[Any], size: int = 2, device=None
) -> Iterator[Any]:
    """Yield items from ``iterator`` staged on device ``size`` items ahead.

    ``size`` bounds host memory (at most ``size`` staged batches + one being
    built). ``device=None`` uses JAX's default placement; pass a
    ``jax.Device`` (or ``NamedSharding``) to pin. ``size <= 0`` disables
    prefetching and yields pass-through (useful to A/B the overlap).
    """
    import jax

    if size <= 0:
        yield from iterator
        return

    q: queue.Queue = queue.Queue(maxsize=size)
    stop = threading.Event()

    def _put(item) -> bool:
        """Bounded put that respects ``stop`` — EVERY producer put goes
        through here (a blocking put of the sentinel/error with a full queue
        and a gone consumer would leak the thread and its staged batches
        for process lifetime)."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def produce():
        try:
            for item in iterator:
                # chaos point: a batcher blowing up mid-stream inside the
                # thread (must surface at the consumer's next(), never hang)
                faults.raise_if("prefetch.producer_raises")
                staged = (
                    jax.device_put(item, device)
                    if device is not None
                    else jax.device_put(item)
                )
                if not _put(staged):
                    return
        except BaseException as e:  # re-raised consumer-side
            _put(_ProducerError(e))
            return
        _put(_SENTINEL)

    t = threading.Thread(target=produce, daemon=True, name="prefetch_to_device")
    t.start()
    try:
        while True:
            item = q.get()
            if item is _SENTINEL:
                return
            if isinstance(item, _ProducerError):
                raise item.exc
            yield item
    finally:
        # Thread-leak fix: stop.set() alone only *asks* the producer to
        # exit — an early-exiting consumer (break / exception / abandoned
        # iterator) used to leave the thread and its staged device batches
        # alive until interpreter exit. The producer's _put loop polls
        # ``stop`` every 0.1 s, so this join completes promptly; the
        # timeout is a backstop against a producer wedged inside
        # device_put, and a still-alive thread after it is a bug worth
        # surfacing loudly.
        stop.set()
        t.join(timeout=5.0)
        if t.is_alive():  # pragma: no cover — requires a wedged device_put
            import warnings

            warnings.warn(
                "prefetch_to_device producer thread failed to exit within 5s",
                RuntimeWarning,
                stacklevel=2,
            )
