"""IVDetect-style subtoken tokenizer.

Parity with ``DDFA/sastvd/helpers/tokenise.py:4-35``: split on any
non-alphanumeric character, then split camelCase boundaries (lower→Upper and
ACRONYMWord boundaries), drop single-character tokens, join with spaces.
"""

from __future__ import annotations

import re

__all__ = ["tokenise", "tokenise_lines"]

_NON_ALNUM = re.compile(r"[^a-zA-Z0-9]+")
_CAMEL = re.compile(
    r".+?(?:(?<=[a-z])(?=[A-Z])|(?<=[A-Z])(?=[A-Z][a-z])|$)"
)


def tokenise(s: str) -> str:
    words = [w for w in _NON_ALNUM.split(s) if w]
    subtokens = [m.group(0) for w in words for m in _CAMEL.finditer(w)]
    return " ".join(t for t in subtokens if len(t) > 1)


def tokenise_lines(s: str) -> list[str]:
    """Per-line tokenisation, empty lines dropped
    (``tokenise.py:23-35``)."""
    out = []
    for line in s.splitlines():
        tok = tokenise(line)
        if tok:
            out.append(tok)
    return out
