"""Data layer: graph containers/batching, dataset readers, vocab, sampling."""
