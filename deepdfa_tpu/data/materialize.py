"""CPG → training-ready graphs (the "dbize" stage).

Covers the reference's materialisation scripts:

- node/edge selection — ``sastvd/linevd/utils.py:28-76`` ``feature_extraction``:
  keep nodes with a line number, restrict edges to the CFG subgraph, drop
  lone nodes, renumber to 0..n-1;
- per-node vulnerability labels — ``sastvd/scripts/dbize.py:30-57``:
  ``vuln = line ∈ removed ∪ dep-add`` for Big-Vul; graph-label broadcast for
  Devign (``:59-81``);
- graph construction — ``sastvd/scripts/dbize_graphs.py:20-33``: the
  reference builds ``dgl.graph((innode, outnode))``, i.e. message passing
  runs **against** CPG edge direction (a CPG CFG edge is outnode→innode);
  our ``Graph(senders=innode, receivers=outnode)`` reproduces that, and
  self-loops are appended as ``dgl.add_self_loop`` does;
- feature attachment — ``linevd/graphmogrifier.py:59-97``: the combined
  ``_ABS_DATAFLOW`` id plus per-subkey ``_ABS_DATAFLOW_{subkey}`` ids.

Output graphs serialise via ``data/graphs.py`` ``save_shards`` (the
``graphs.bin`` replacement).
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Mapping

import numpy as np
import pandas as pd

from deepdfa_tpu.config import ALL_SUBKEYS, DFA_FEATURE_DIMS, FeatureConfig
from deepdfa_tpu.cpg.features import (
    dataflow_node_features,
    extract_features,
    features_to_hashes,
)
from deepdfa_tpu.cpg.schema import CPG
from deepdfa_tpu.data.graphs import Graph
from deepdfa_tpu.data.vocab import Vocabulary, build_vocab

__all__ = ["select_cfg_nodes", "graph_from_cpg", "CorpusBuilder"]


def select_cfg_nodes(
    cpg: CPG, gtype: str = "cfg"
) -> tuple[list[int], list[tuple[int, int]]]:
    """(ordered node ids, edge list) after the reference's selection: nodes
    need a line number, edges are the deduped ``gtype`` subgraph
    (``rdg``, golden config = cfg) between kept nodes, lone nodes dropped."""
    from deepdfa_tpu.cpg.schema import rdg

    with_line = [i for i, n in cpg.nodes.items() if n.line is not None]
    keep = set(with_line)
    edges = [(s, d) for s, d in rdg(cpg, gtype) if s in keep and d in keep]
    connected = {s for s, _ in edges} | {d for _, d in edges}
    nodes = [i for i in with_line if i in connected]
    return nodes, edges


def graph_from_cpg(
    cpg: CPG,
    gid: int,
    feat_ids: Mapping[str, Mapping[int, int]],
    vuln_lines: set[int] | None = None,
    graph_label: int | None = None,
    gtype: str = "cfg",
    dataflow_labels: bool = False,
    selection: tuple[list, list] | None = None,
) -> Graph | None:
    """Build one training graph. ``feat_ids`` maps feature name →
    {node_id: int id}. Exactly one of ``vuln_lines`` (per-line labels,
    Big-Vul) / ``graph_label`` (broadcast, Devign) must be given.

    ``selection``: a precomputed ``select_cfg_nodes(cpg, gtype)`` result.
    Callers that need the node ORDER themselves (`predict` maps node index
    → source line) pass it in, so the order used for features and the
    order used for attribution are the same object by construction.

    Returns None when no graph structure survives selection (the reference
    drops such graphs at load time, ``linevd/dataset.py:40-45``).
    """
    nodes, edges = selection if selection is not None else select_cfg_nodes(cpg, gtype)
    if not nodes:
        return None
    pos = {nid: i for i, nid in enumerate(nodes)}
    # reference direction: dgl.graph((innode, outnode)) — message source is
    # the CPG edge's *destination* (innode).
    senders = np.array([pos[d] for _, d in edges], dtype=np.int32)
    receivers = np.array([pos[s] for s, _ in edges], dtype=np.int32)

    if (vuln_lines is None) == (graph_label is None):
        raise ValueError("exactly one of vuln_lines/graph_label required")
    if vuln_lines is not None:
        vuln = np.array(
            [1 if cpg.nodes[n].line in vuln_lines else 0 for n in nodes],
            dtype=np.int32,
        )
    else:
        vuln = np.full(len(nodes), int(graph_label), dtype=np.int32)

    feats: dict[str, np.ndarray] = {"_VULN": vuln}
    for name, ids in feat_ids.items():
        feats[name] = np.array([ids.get(n, 0) for n in nodes], dtype=np.int32)

    if dataflow_labels:
        # Per-node reaching-definitions solution bits, the DFA-learning
        # targets (label_style=dataflow_solution_{in,out}). The reference's
        # hooks expect [|V|] 0/1 ndata (``main_cli.py:250-254``) but this
        # snapshot never materialises them — our solver does: 1 iff the
        # node's IN (resp. OUT) set is non-empty. ``add_dependence_edges``
        # caches its fixpoint on the CPG; only un-augmented graphs re-solve.
        cached = getattr(cpg, "rd_solution", None)
        if cached is not None:
            in_sets, out_sets = cached
        else:
            from deepdfa_tpu.cpg.dataflow import ReachingDefinitions

            in_sets, out_sets = ReachingDefinitions(cpg).solve()
        feats["_DF_IN"] = np.array(
            [1 if in_sets.get(n) else 0 for n in nodes], dtype=np.int32
        )
        feats["_DF_OUT"] = np.array(
            [1 if out_sets.get(n) else 0 for n in nodes], dtype=np.int32
        )

    g = Graph(senders=senders, receivers=receivers, node_feats=feats, gid=gid)
    return g.with_self_loops()


@dataclasses.dataclass
class CorpusBuilder:
    """End-to-end feature pipeline over an in-memory corpus of CPGs.

    Run order matches ``DDFA/scripts/preprocess.sh``: stage-1/2 feature
    extraction → train-split vocab → per-node encoding → graph emission.
    One instance per :class:`FeatureConfig`; per-subkey features reuse the
    same extraction with single-subkey configs (``dbize_absdf.py:21-33``'s
    feature grid collapses to the configs actually requested).
    """

    feature: FeatureConfig = dataclasses.field(default_factory=FeatureConfig)
    concat_all_absdf: bool = True

    def extract(self, cpgs: Mapping[int, CPG], raise_all: bool = False) -> pd.DataFrame:
        """Stage 1+2: per-definition hash table for the whole corpus."""
        frames = []
        for gid, cpg in cpgs.items():
            f = extract_features(cpg, gid, raise_all=raise_all)
            if len(f):
                frames.append(f)
        if not frames:
            return pd.DataFrame(columns=["graph_id", "node_id", "hash"])
        feats = pd.concat(frames, ignore_index=True)
        return features_to_hashes(feats, self.feature.subkeys)

    def vocabs(
        self, hash_df: pd.DataFrame, train_ids: Iterable[int]
    ) -> dict[str, Vocabulary]:
        """The combined vocab plus one single-subkey vocab per subkey when
        ``concat_all_absdf`` (each with the same limits, as in the
        reference's feature grid)."""
        train_ids = list(train_ids)
        out = {"_ABS_DATAFLOW": build_vocab(hash_df, train_ids, self.feature)}
        if self.concat_all_absdf:
            for sk in ALL_SUBKEYS:
                cfg = dataclasses.replace(self.feature, subkeys=(sk,))
                out[f"_ABS_DATAFLOW_{sk}"] = build_vocab(hash_df, train_ids, cfg)
        return out

    def build(
        self,
        cpgs: Mapping[int, CPG],
        train_ids: Iterable[int],
        vuln_lines: Mapping[int, set[int]] | None = None,
        graph_labels: Mapping[int, int] | None = None,
        raise_all: bool = False,
        dataflow_labels: bool = False,
    ) -> tuple[list[Graph], dict[str, Vocabulary]]:
        """Full pipeline; returns (graphs, vocabs). Graphs with no CFG are
        dropped (counted by comparing lengths)."""
        hash_df = self.extract(cpgs, raise_all=raise_all)
        # kept for the coverage analyzer (train/cli.py variant_coverage):
        # scripts/preprocess.py persists it as hashes.parquet so `analyze`
        # can rebuild the limit_all x subkey vocab grid without re-extraction
        self.hash_df = hash_df
        vocabs = self.vocabs(hash_df, train_ids)
        by_graph: dict[int, dict[int, str]] = {}
        for row in hash_df.itertuples(index=False):
            by_graph.setdefault(int(row.graph_id), {})[int(row.node_id)] = row.hash

        graphs: list[Graph] = []
        for gid, cpg in cpgs.items():
            hashes = by_graph.get(int(gid), {})
            feat_ids = {
                name: {n: voc.feature_id(h) for n, h in hashes.items()}
                for name, voc in vocabs.items()
            }
            if self.feature.dataflow_families:
                # static-analysis families: no vocab, raw values clipped into
                # their fixed embedding-table range (config.DFA_FEATURE_DIMS)
                for fam, values in dataflow_node_features(cpg).items():
                    dim = DFA_FEATURE_DIMS[fam]
                    feat_ids[f"_DFA_{fam}"] = {
                        n: min(max(int(v), 0), dim - 1) for n, v in values.items()
                    }
            if self.feature.interproc_families:
                # interprocedural families run per-graph: a corpus graph is
                # one parse unit (a file's functions), so the supergraph is
                # built over that unit only — no cross-graph call resolution
                from deepdfa_tpu.cpg.interproc import interproc_node_features

                for fam, values in interproc_node_features(cpg).items():
                    dim = DFA_FEATURE_DIMS[fam]
                    feat_ids[f"_DFA_{fam}"] = {
                        n: min(max(int(v), 0), dim - 1) for n, v in values.items()
                    }
            g = graph_from_cpg(
                cpg,
                gid,
                feat_ids,
                vuln_lines=set(vuln_lines[gid]) if vuln_lines is not None else None,
                graph_label=graph_labels[gid] if graph_labels is not None else None,
                dataflow_labels=dataflow_labels,
            )
            if g is not None:
                graphs.append(g)
        return graphs, vocabs
