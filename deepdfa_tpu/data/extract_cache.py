"""On-disk content-addressed extraction cache: normalized source → CPG payload.

Per-function CPG extraction dominates corpus-build wall clock (the
reference sharded it over a 0–99 SLURM array), yet most rebuilds touch a
handful of functions. This cache makes a re-scan pay only for *changed*
functions: entries are keyed on :func:`deepdfa_tpu.pipeline.source_key`
(the same whitespace-normalized sha256 the serve scan cache uses — a
whitespace-only edit shares the entry) salted with an extractor-version /
vocab component, so bumping the frontend or re-vocabing a corpus misses
cleanly instead of serving stale graphs.

Commit protocol (ROADMAP invariants 1/10, the checkpoint/warm-store
discipline): the pickled payload lands FIRST via ``atomic_write_bytes``,
then the ``{key}.json`` meta marker commits the entry via
``atomic_write_text``. An entry exists iff its meta exists; a torn write,
a missing payload, a meta/payload digest mismatch or an unpicklable blob
all read as a MISS — never as a decode crash (the ``extract.cache_corrupt``
chaos point pins it). Writers race benignly: both write identical content
under content-addressed names, last ``os.replace`` wins.
"""

from __future__ import annotations

import hashlib
import json
import pickle
import threading
from dataclasses import dataclass
from pathlib import Path

from deepdfa_tpu.resilience import faults
from deepdfa_tpu.resilience.journal import atomic_write_bytes, atomic_write_text

__all__ = ["EXTRACTOR_VERSION", "ExtractCache"]

# Bump when the extraction pipeline's OUTPUT changes shape/content for the
# same source text (frontend node schema, dependence-edge pass, feature
# extraction) — old entries then miss instead of resurrecting stale CPGs.
EXTRACTOR_VERSION = 1


@dataclass
class _Stats:
    hits: int = 0
    misses: int = 0
    corrupt: int = 0
    puts: int = 0


class ExtractCache:
    """``key(code) -> get/put`` over one directory of committed entries."""

    def __init__(self, root: str | Path, *,
                 version: int = EXTRACTOR_VERSION, salt: str = ""):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        # the extractor-version/vocab-salt key component: folded into every
        # key so entries from a different pipeline generation cannot collide
        self._salt = hashlib.sha256(
            f"extractor-v{int(version)}:{salt}".encode()).hexdigest()[:16]
        self._lock = threading.Lock()
        self._stats = _Stats()

    # -- keys ---------------------------------------------------------------
    def key(self, code: str) -> str:
        """Content address of one function/file source under this cache's
        pipeline generation (``source_key`` ⊕ version/vocab salt)."""
        from deepdfa_tpu.pipeline import source_key

        return hashlib.sha256(
            f"{source_key(code)}:{self._salt}".encode()).hexdigest()

    def _paths(self, key: str) -> tuple[Path, Path]:
        return self.root / f"{key}.pkl", self.root / f"{key}.json"

    # -- protocol -----------------------------------------------------------
    def get(self, key: str):
        """The committed payload for ``key``, or None (MISS). Any torn,
        corrupt or injected-corrupt entry is a MISS, never an exception."""
        payload_path, meta_path = self._paths(key)
        try:
            meta = json.loads(meta_path.read_text())
            blob = payload_path.read_bytes()
            if faults.fire("extract.cache_corrupt"):
                blob = blob[: len(blob) // 2] + b"\x00corrupt"
            if meta.get("sha256") != hashlib.sha256(blob).hexdigest():
                raise ValueError("payload digest mismatch")
            value = pickle.loads(blob)
        except FileNotFoundError:
            with self._lock:
                self._stats.misses += 1
            return None
        except Exception:  # noqa: BLE001 — corrupt entry == miss, by design
            with self._lock:
                self._stats.misses += 1
                self._stats.corrupt += 1
            return None
        with self._lock:
            self._stats.hits += 1
        return value

    def put(self, key: str, value) -> None:
        """Commit payload-first: the ``{key}.json`` meta marker is written
        only after the pickled payload is durably in place."""
        payload_path, meta_path = self._paths(key)
        blob = pickle.dumps(value)
        atomic_write_bytes(payload_path, blob)
        atomic_write_text(meta_path, json.dumps({
            "schema": 1,
            "sha256": hashlib.sha256(blob).hexdigest(),
            "bytes": len(blob),
        }))
        with self._lock:
            self._stats.puts += 1

    def get_or_extract(self, code: str, extract):
        """``(value, hit)`` — the committed payload for ``code``, or
        ``extract(code)`` committed on the way out."""
        k = self.key(code)
        value = self.get(k)
        if value is not None:
            return value, True
        value = extract(code)
        self.put(k, value)
        return value, False

    # -- accounting ---------------------------------------------------------
    def __len__(self) -> int:
        return sum(1 for _ in self.root.glob("*.json"))

    def stats(self) -> dict:
        with self._lock:
            s = self._stats
            lookups = s.hits + s.misses
            return {
                "hits": s.hits,
                "misses": s.misses,
                "corrupt": s.corrupt,
                "puts": s.puts,
                "hit_rate": (s.hits / lookups) if lookups else 0.0,
            }
