"""Synthetic CFG-like graph generator.

Hermetic stand-in for Big-Vul-shaped data: the real corpus requires a network
download (``scripts/download_all.sh`` in the reference) which is unavailable
here, so tests, smoke training and benchmarks use graphs drawn to match
Big-Vul's scale (mean ~50 CFG nodes/function, heavy tail; self-loops added as
in ``dbize_graphs.py:26``). Features follow the abstract-dataflow contract:
per-node integer ids in ``[0, input_dim)`` with 0 = not-a-definition, and a
``_VULN`` node label whose graph-level max defines the class.
"""

from __future__ import annotations

import numpy as np

from deepdfa_tpu.config import ALL_SUBKEYS, DFA_FEATURE_DIMS, active_dfa_families
from deepdfa_tpu.data.graphs import Graph

__all__ = ["random_graph", "random_dataset"]


def random_graph(
    rng: np.random.Generator,
    input_dim: int = 1002,
    mean_nodes: int = 50,
    vul: bool | None = None,
    def_rate: float = 0.35,
    dataflow_families: bool = False,
    interproc_families: bool = False,
) -> Graph:
    n = max(3, int(rng.lognormal(mean=np.log(mean_nodes), sigma=0.6)))
    # CFG backbone: a chain with branch/merge shortcuts, like real control flow.
    senders = list(range(n - 1))
    receivers = list(range(1, n))
    n_extra = max(1, n // 8)
    src = rng.integers(0, n - 1, size=n_extra)
    dst = np.minimum(src + rng.integers(2, 5, size=n_extra), n - 1)
    senders += src.tolist()
    receivers += dst.tolist()

    is_def = rng.random(n) < def_rate
    feats: dict[str, np.ndarray] = {}
    for sk in ALL_SUBKEYS:
        ids = rng.integers(1, input_dim, size=n, dtype=np.int32)
        feats[f"_ABS_DATAFLOW_{sk}"] = np.where(is_def, ids, 0).astype(np.int32)
    # Combined-vocab id (the golden-config feature `_ABS_DATAFLOW..._all`).
    ids = rng.integers(1, input_dim, size=n, dtype=np.int32)
    feats["_ABS_DATAFLOW"] = np.where(is_def, ids, 0).astype(np.int32)

    for fam in active_dfa_families(dataflow_families, interproc_families):
        # static-analysis families (config.DFA_FAMILIES / IDFA_FAMILIES):
        # values drawn from each family's closed range, like preprocess
        # emits them
        feats[f"_DFA_{fam}"] = rng.integers(
            0, DFA_FEATURE_DIMS[fam], size=n, dtype=np.int32
        )

    if vul is None:
        vul = bool(rng.random() < 0.06)
    vuln = np.zeros(n, dtype=np.int32)
    if vul:
        # Mark 1-3 "vulnerable statements"; make them weakly learnable by
        # biasing the api feature id into a reserved low band.
        k = int(rng.integers(1, 4))
        idx = rng.choice(n, size=min(k, n), replace=False)
        vuln[idx] = 1
        feats["_ABS_DATAFLOW_api"][idx] = rng.integers(1, 1 + max(2, input_dim // 50))
    feats["_VULN"] = vuln

    g = Graph(
        senders=np.array(senders, dtype=np.int32),
        receivers=np.array(receivers, dtype=np.int32),
        node_feats=feats,
    )
    return g.with_self_loops()


def random_dataset(
    n_graphs: int,
    seed: int = 0,
    input_dim: int = 1002,
    mean_nodes: int = 50,
    vul_rate: float = 0.06,
    dataflow_families: bool = False,
    interproc_families: bool = False,
) -> list[Graph]:
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n_graphs):
        g = random_graph(
            rng, input_dim=input_dim, mean_nodes=mean_nodes,
            vul=bool(rng.random() < vul_rate),
            dataflow_families=dataflow_families,
            interproc_families=interproc_families,
        )
        g.gid = i
        out.append(g)
    return out
