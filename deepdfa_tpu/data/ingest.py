"""Dataset ingestion: raw corpora → labeled function tables.

Host-side (pure pandas/difflib) re-design of the reference's ingestion stack:

- comment stripping            — ``DDFA/sastvd/helpers/datasets.py:19-33``
- Big-Vul CSV reader + filters — ``datasets.py:139-292``
- Devign JSON reader           — ``datasets.py:36-102``
- mutated variants             — ``datasets.py:105-126``
- diff labeling                — ``helpers/git.py:12-165`` (the reference
  shells out to ``git diff --no-index``; we compute the same combined-view
  line labels with ``difflib`` — no subprocess, no temp files, same contract:
  1-based line numbers into the *combined* before+after view)
- validity / file filters      — ``datasets.py:295-405``
- split maps + partitioning    — ``datasets.py:431-523``
- dataset class w/ resampling  — ``helpers/dclass.py:18-118`` (the per-epoch
  index draw itself lives in ``deepdfa_tpu/data/sampler.py``)

Artifacts are cached under ``cache_dir()/minimal_datasets`` like the
reference's minimal parquet cache (``datasets.py:144-156``); the format is
parquet when an engine is available, pickle otherwise.
"""

from __future__ import annotations

import difflib
import json
import re
from glob import glob
from pathlib import Path
from typing import Callable, Iterable

import numpy as np
import pandas as pd

from deepdfa_tpu import utils

__all__ = [
    "remove_comments",
    "diff_lines",
    "label_diffs",
    "bigvul",
    "devign",
    "ds",
    "itempath",
    "check_validity",
    "filter_dataset",
    "linevul_splits",
    "codexglue_splits",
    "named_splits",
    "splits_map",
    "partition",
    "validate_cpgs",
    "QUARANTINE_FILE",
    "read_quarantine",
    "write_quarantine",
    "VulnDataset",
]

# ---------------------------------------------------------------------------
# comment stripping


_COMMENT_OR_STRING = re.compile(
    # string literals first so comment markers inside them survive
    r'"(?:\\.|[^"\\])*"'
    r"|'(?:\\.|[^'\\])*'"
    r"|/\*.*?\*/"
    r"|//[^\n]*",
    re.DOTALL,
)


def remove_comments(text: str) -> str:
    """Strip ``//`` and ``/* */`` comments from C code, leaving string
    literals intact. Comments become a single space (so token boundaries and
    byte offsets inside a line stay sane), exactly like the reference
    (``datasets.py:19-33`` replaces with ``" "``, not ``""``)."""

    def _repl(m: re.Match) -> str:
        s = m.group(0)
        return " " if s.startswith("/") else s

    return _COMMENT_OR_STRING.sub(_repl, text)


# ---------------------------------------------------------------------------
# diff labeling (combined-view line numbers)


def diff_lines(before: str, after: str) -> dict:
    """Combined diff of two function versions.

    Returns ``{"diff", "added", "removed", "before", "after"}`` where

    - ``diff`` is the hunk body: every line of the combined view prefixed
      with ``" "``, ``"-"`` (only in before) or ``"+"`` (only in after);
    - ``added`` / ``removed`` are 1-based line numbers **into the combined
      view** (parity with ``git.py:74-79``, which indexes the single full-
      context hunk the reference requests with ``-U<total>``);
    - ``before`` / ``after`` are the combined views with the other side's
      lines commented out (``git.py:128-165`` ``allfunc``), so line numbers
      in both versions agree with the combined numbering — this is what makes
      per-line vulnerability labels transferable to the CPG.
    """
    old_lines = before.splitlines()
    new_lines = after.splitlines()
    sm = difflib.SequenceMatcher(a=old_lines, b=new_lines, autojunk=False)
    diff: list[str] = []
    added: list[int] = []
    removed: list[int] = []
    view_before: list[str] = []
    view_after: list[str] = []
    for tag, i1, i2, j1, j2 in sm.get_opcodes():
        if tag in ("equal",):
            for line in old_lines[i1:i2]:
                diff.append(" " + line)
                view_before.append(line)
                view_after.append(line)
        else:
            for line in old_lines[i1:i2]:
                diff.append("-" + line)
                removed.append(len(diff))
                view_before.append(line)
                view_after.append("// " + line)
            for line in new_lines[j1:j2]:
                diff.append("+" + line)
                added.append(len(diff))
                view_before.append("// " + line)
                view_after.append(line)
    return {
        "diff": "\n".join(diff),
        "added": added,
        "removed": removed,
        "before": "\n".join(view_before),
        "after": "\n".join(view_after),
    }


def _label_one(item: tuple) -> dict:
    func_before, func_after = item
    if func_before == func_after:
        return {
            "diff": "",
            "added": [],
            "removed": [],
            "before": func_before,
            "after": func_before,
        }
    return diff_lines(func_before, func_after)


def label_diffs(df: pd.DataFrame, workers: int = 6) -> pd.DataFrame:
    """Attach diff/added/removed/before/after columns (parallel host map,
    replacing the reference's per-id pickle cache + git subprocess fan-out,
    ``datasets.py:207-217``)."""
    infos = utils.dfmp(
        df, _label_one, columns=["func_before", "func_after"], workers=workers,
        desc="diff: ",
    )
    info_df = pd.DataFrame(infos, index=df.index)
    return pd.concat([df.drop(columns=info_df.columns, errors="ignore"), info_df], axis=1)


# ---------------------------------------------------------------------------
# cache IO (parquet if an engine exists, else pickle)


def _cache_path(name: str, sample: bool) -> Path:
    d = utils.get_dir(utils.cache_dir() / "minimal_datasets")
    return d / f"minimal_{name}{'_sample' if sample else ''}"


def _cache_save(df: pd.DataFrame, base: Path) -> Path:
    try:
        path = base.with_suffix(".pq")
        df.to_parquet(path, index=False)
        return path
    except Exception:
        path = base.with_suffix(".pkl")
        df.to_pickle(path)
        return path


def _cache_load(base: Path) -> pd.DataFrame | None:
    for suffix in (".pq", ".pkl"):
        path = base.with_suffix(suffix)
        if path.exists():
            try:
                if suffix == ".pq":
                    return pd.read_parquet(path).dropna()
                return pd.read_pickle(path).dropna()
            except Exception:
                continue
    return None


# ---------------------------------------------------------------------------
# readers


_MINIMAL_COLS = ["id", "before", "after", "removed", "added", "diff", "vul", "dataset"]


def _abnormal_ending(code: str) -> bool:
    """Functions that do not end in ``}``/``;`` were truncated upstream
    (``datasets.py:223-238``). The separate ``");"`` filter applies only to
    the combined before view (``datasets.py:238``), not here."""
    stripped = code.strip()
    return not stripped or stripped[-1] not in ("}", ";")


def bigvul(
    csv_path: str | Path | None = None,
    cache: bool = True,
    sample: bool = False,
    workers: int = 6,
) -> pd.DataFrame:
    """Big-Vul (MSR) reader: CSV → comment-strip → diff labels → quality
    filters → minimal table (``datasets.py:139-292``).

    Quality filters applied to vulnerable rows only (non-vul rows pass):
    no-change diffs, abnormal endings, modified-proportion ≥ 0.7, ≤ 5 lines.
    """
    base = _cache_path("bigvul", sample)
    if cache and csv_path is None:
        cached = _cache_load(base)
        if cached is not None:
            return cached
    default_source = csv_path is None
    if csv_path is None:
        name = "MSR_data_cleaned_SAMPLE.csv" if sample else "MSR_data_cleaned.csv"
        csv_path = utils.external_dir() / name
    df = pd.read_csv(csv_path, dtype={"commit_id": str, "project": str})
    if "Unnamed: 0" in df.columns:
        df = df.rename(columns={"Unnamed: 0": "id"})
    if "id" not in df.columns:
        df = df.rename_axis("id").reset_index()
    df["dataset"] = "bigvul"
    df["vul"] = df["vul"].astype(int)

    df["func_before"] = utils.dfmp(
        df, remove_comments, columns="func_before", workers=workers, cs=500,
        desc="strip: ",
    )
    df["func_after"] = utils.dfmp(
        df, remove_comments, columns="func_after", workers=workers, cs=500,
        desc="strip: ",
    )
    df = label_diffs(df, workers=workers)

    dfv = df[df.vul == 1]
    dfv = dfv[dfv.apply(lambda r: len(r.added) + len(r.removed) > 0, axis=1)]
    dfv = dfv[~dfv.func_before.apply(_abnormal_ending)]
    dfv = dfv[~dfv.func_after.apply(_abnormal_ending)]
    dfv = dfv[~dfv.before.apply(lambda c: c.strip().endswith(");"))]
    if len(dfv):
        mod_prop = dfv.apply(
            lambda r: (len(r.added) + len(r.removed))
            / max(len(r["diff"].splitlines()), 1),
            axis=1,
        )
        dfv = dfv[mod_prop < 0.7]
    if len(dfv):
        dfv = dfv[dfv.before.apply(lambda c: len(c.splitlines()) > 5)]
    keep_vul = set(dfv["id"])
    df = df[(df.vul == 0) | (df["id"].isin(keep_vul))].copy()

    out = df[_MINIMAL_COLS].reset_index(drop=True)
    # Only the canonical source may populate the shared cache; a custom
    # csv_path (subsets, tests) must not poison later default loads.
    if cache and default_source:
        _cache_save(out, base)
    return out


def devign(
    json_path: str | Path | None = None, cache: bool = True, sample: bool = False
) -> pd.DataFrame:
    """Devign reader: ``function.json`` → graph-level labels only
    (``datasets.py:36-102``); no line labels (no before/after pairs)."""
    base = _cache_path("devign", sample)
    if cache and json_path is None:
        cached = _cache_load(base)
        if cached is not None:
            return cached
    default_source = json_path is None
    if json_path is None:
        json_path = utils.external_dir() / "function.json"
    df = pd.read_json(json_path)
    df = df.rename_axis("id").reset_index()
    df["dataset"] = "devign"
    df["before"] = [remove_comments(c).replace("\n\n", "\n") for c in df["func"]]
    df = df[~df.before.apply(_abnormal_ending)]
    df = df[~df.before.apply(lambda c: c.strip().endswith(");"))]
    df["vul"] = df["target"].astype(int)
    if sample:
        df = df.head(50)
    out = df[["id", "dataset", "before", "target", "vul"]].reset_index(drop=True)
    if cache and default_source:
        _cache_save(out, base)
    return out


def diversevul(
    json_path: str | Path | None = None, cache: bool = True, sample: bool = False
) -> pd.DataFrame:
    """DiverseVul reader (config #4's corpus; the reference's finetuned
    checkpoints are tuned on it — ``MSIVD/msivd/train.py:863-869`` consumes
    them). Source: the published ``diversevul_*.json`` JSONL — one object
    per function: ``func``, ``target``, ``cwe`` (list), ``project``,
    ``commit_id``, ``message``. Keeps the explanation columns (``cwe``,
    ``message``) that the self-instruct multitask builder supervises on."""
    base = _cache_path("diversevul", sample)
    if cache and json_path is None:
        cached = _cache_load(base)
        if cached is not None:
            return cached
    default_source = json_path is None
    if json_path is None:
        json_path = utils.external_dir() / "diversevul.json"
    df = pd.read_json(json_path, lines=True)
    df = df.rename_axis("id").reset_index()
    df["dataset"] = "diversevul"
    df["before"] = [remove_comments(c).replace("\n\n", "\n") for c in df["func"]]
    df = df[~df.before.apply(_abnormal_ending)]
    df["vul"] = df["target"].astype(int)

    def _clean(v) -> str:
        # null/NaN-safe: pd.read_json yields float NaN for missing values,
        # and NaN is truthy — naive str(v or "") would supervise the literal
        # answer "nan" in the explanation rounds
        if isinstance(v, (list, tuple)):
            return ",".join(str(x) for x in v)
        if v is None or (isinstance(v, float) and pd.isna(v)):
            return ""
        return str(v)

    cwe_col = df["cwe"] if "cwe" in df.columns else pd.Series("", index=df.index)
    df["cwe"] = [_clean(v) for v in cwe_col]
    msg_col = df["message"] if "message" in df.columns else pd.Series("", index=df.index)
    df["message"] = [_clean(v) for v in msg_col]
    if sample:
        df = df.head(50)
    out = df[
        ["id", "dataset", "before", "target", "vul", "cwe", "message"]
    ].reset_index(drop=True)
    if cache and default_source:
        _cache_save(out, base)
    return out


def mutated(
    subdataset: str, cache: bool = True, sample: bool = False
) -> pd.DataFrame:
    """Mutation-robustness variants: Big-Vul rows joined with mutated sources
    (``datasets.py:105-126``). ``*_flip`` uses the mutation *source* column."""
    df = bigvul(cache=cache, sample=sample).drop(columns=["dataset", "before"])
    fp = utils.external_dir() / "mutated" / f"c_{subdataset.replace('_flip', '')}.jsonl"
    mut = pd.read_json(fp, lines=True)
    col = "source" if "flip" in subdataset else "target"
    mut = mut.rename(columns={col: "before"}).drop(
        columns=[c for c in ("source", "target") if c != col], errors="ignore"
    )
    df = pd.merge(df, mut, left_on="id", right_on="idx", how="inner")
    df["dataset"] = f"mutated_{subdataset}"
    return df.drop(columns=["after", "added", "removed", "diff"], errors="ignore")


def ds(dsname: str, cache: bool = True, sample: bool = False, **kw) -> pd.DataFrame:
    """Dataset dispatcher (``datasets.py:129-137``)."""
    if dsname == "bigvul":
        return bigvul(cache=cache, sample=sample, **kw)
    if dsname == "devign":
        return devign(cache=cache, sample=sample, **kw)
    if dsname == "diversevul":
        return diversevul(cache=cache, sample=sample, **kw)
    if dsname.startswith("mutated"):
        return mutated(dsname.split("_", maxsplit=1)[1], cache=cache, sample=sample)
    raise ValueError(f"unknown dataset {dsname!r}")


# ---------------------------------------------------------------------------
# extraction-artifact filters


def itempath(_id, dsname: str = "bigvul") -> Path:
    """Path of the per-function source file whose extraction artifacts
    (``.nodes.json``/``.edges.json``/``.dataflow.json``) sit next to it
    (``datasets.py:333-335``)."""
    return utils.processed_dir() / dsname / "before" / f"{_id}.c"


def check_validity(
    _id,
    dsname: str = "bigvul",
    require_line_number: bool = False,
    require_dataflow: bool = False,
) -> bool:
    """A sample is valid when its extracted graph parses, has ≥1 node with a
    line number, and (optionally) has dataflow edges (``datasets.py:295-330``)."""
    path = itempath(_id, dsname)
    try:
        with open(f"{path}.nodes.json") as f:
            nodes = json.load(f)
        with open(f"{path}.edges.json") as f:
            edges = json.load(f)
    except Exception:
        return False
    if not nodes or not edges:
        return False
    if not any("lineNumber" in n for n in nodes):
        if require_line_number:
            return False
    etypes = {e[2] for e in edges}
    if require_dataflow and not ({"REACHING_DEF", "CDG"} & etypes):
        return False
    return True


def filter_dataset(
    df: pd.DataFrame,
    dsname: str,
    check_file: bool = False,
    check_valid: bool = False,
    vulonly: bool = False,
    load_code: bool = True,
    sample: int = -1,
    sample_mode: bool = False,
    seed: int = 0,
    validity_fn: Callable | None = None,
) -> pd.DataFrame:
    """Training-time dataset filters (``datasets.py:352-405``): optional random
    subsample, vul-only, drop rows with no extraction artifacts on disk, drop
    rows failing validity (with a CSV cache so re-runs skip the scan)."""
    if sample > 0:
        df = df.sample(sample, random_state=seed)
    if vulonly:
        df = df[df.vul == 1]
    if check_file:
        have = {
            int(Path(p).name.split(".")[0])
            for p in glob(str(utils.processed_dir() / dsname / "before" / "*.nodes.json"))
            if not Path(p).name.startswith("~")
        }
        df = df[df.id.isin(have)]
    if check_valid:
        # A custom validity_fn bypasses the shared cache: the cache file is
        # keyed only by (dsname, sample_mode) and must stay tied to the
        # default check (the reference has no validity_fn hook to collide).
        if validity_fn is not None:
            valid = [validity_fn(i) for i in df.id]
            df = df[pd.Series(valid, index=df.index)]
        else:
            cache = utils.cache_dir() / f"{dsname}_valid_{sample_mode}.csv"
            if cache.exists():
                valid_df = pd.read_csv(cache, index_col=0)
            else:
                valid = [check_validity(i, dsname) for i in df.id]
                valid_df = pd.DataFrame({"id": df.id, "valid": valid}, index=df.index)
                valid_df.to_csv(cache)
            df = df[df.id.isin(valid_df[valid_df["valid"]].id)]
    assert len(df) > 0, "all rows filtered out"
    if not load_code:
        df = df.drop(
            columns=["before", "after", "removed", "added", "diff"], errors="ignore"
        )
    return df


# ---------------------------------------------------------------------------
# splits


def linevul_splits(path: str | Path | None = None) -> pd.Series:
    """Fixed Big-Vul splits (LineVul protocol): id-indexed train/val/test
    (``datasets.py:449-454``)."""
    path = path or utils.external_dir() / "linevul_splits.csv"
    s = pd.read_csv(path, index_col=0)["split"]
    return s.replace("valid", "val")


def codexglue_splits(path: str | Path | None = None) -> pd.Series:
    """Fixed Devign splits (CodeXGLUE protocol) (``datasets.py:457-462``)."""
    path = path or utils.external_dir() / "codexglue_splits.csv"
    df = pd.read_csv(path).set_index("example_index")
    return df["split"].replace("valid", "val")


def named_splits(name: str, path: str | Path | None = None) -> pd.Series:
    """Named cross-project split files (``datasets.py:465-473``); ``holdout``
    folds into ``test``."""
    path = path or utils.external_dir() / "splits" / f"{name}.csv"
    df = pd.read_csv(path, index_col=0).set_index("example_index")
    return df["split"].replace({"valid": "val", "holdout": "test"})


def partition_ids(ids, smap: dict) -> tuple[dict[str, list], int]:
    """Bucket ``ids`` by a split map into train/val/test; ids the map does
    not assign are EXCLUDED from every split (the reference drops unmapped
    rows at load) and counted. ONE implementation for preprocess-time and
    load-time partitioning — the protocol must not be defined twice."""
    splits: dict[str, list] = {"train": [], "val": [], "test": []}
    unassigned = 0
    for fid in ids:
        part = smap.get(fid)
        if part in splits:
            splits[part].append(fid)
        else:
            unassigned += 1
    return splits, unassigned


def splits_map(dsname: str) -> dict:
    """Default fixed-split map per dataset (``datasets.py:431-438``)."""
    if dsname == "bigvul" or dsname.startswith("mutated"):
        return linevul_splits().to_dict()
    if dsname == "devign":
        return codexglue_splits().to_dict()
    raise ValueError(dsname)


def partition(
    df: pd.DataFrame,
    part: str,
    dsname: str = "bigvul",
    split: str = "fixed",
    seed: int = 0,
    splits: dict | None = None,
) -> pd.DataFrame:
    """Label rows with train/val/test and optionally select one partition
    (``datasets.py:475-520``).

    ``split="random"``: hold out the *fixed* test set entirely, then assign
    val/test/train as 10/10/80% of a seed-deterministic permutation — same
    construction as the reference, so same seed ⇒ same split.
    """
    df = df.copy()
    if split == "random":
        smap = splits if splits is not None else splits_map(dsname)
        fixed = df.id.map(smap)
        df = df[fixed != "test"].copy()
        n = len(df)
        perm = np.random.RandomState(seed=seed).permutation(df.index.to_numpy())
        n_val = int(n * 0.1)
        n_test = int(n * 0.2)
        # Reference quirk parity (datasets.py:489-500): position i in the
        # *unpermuted* range decides the label; the permutation decides which
        # row gets position i.
        df["label"] = pd.Series(
            ["val" if i < n_val else "test" if i < n_test else "train" for i in range(n)],
            index=perm,
        )
    elif split == "fixed":
        smap = splits if splits is not None else splits_map(dsname)
        df["label"] = df.id.map(smap)
    elif split == "linevul":
        # LineVD random splits file (the reference's split="linevul" branch,
        # datasets.py:506-509, reading bigvul_rand_splits.csv).
        smap = splits if splits is not None else pd.read_csv(
            utils.external_dir() / "bigvul_rand_splits.csv"
        ).set_index("id")["split"].to_dict()
        df["label"] = df.id.map(smap)
    else:
        smap = splits if splits is not None else named_splits(split).to_dict()
        df["label"] = df.id.map(smap)
    if part != "all":
        df = df[df.label == part]
    return df


# ---------------------------------------------------------------------------
# structural validation at ingestion


def validate_cpgs(cpgs: dict, drop_errors: bool = True) -> tuple[dict, dict]:
    """Run the CPG structural validator (``cpg/validate.py``) over an
    ingested ``{graph_id: CPG}`` corpus.

    Returns ``(kept_cpgs, summary)``: graphs with error-severity diagnostics
    are dropped from ``kept_cpgs`` when ``drop_errors`` (the ingestion
    default — a malformed graph silently corrupts features downstream,
    see the validator's module docstring); the summary is
    ``validate_corpus``'s per-check aggregate, suitable for the per-dataset
    report ``scripts/preprocess.py`` prints.
    """
    from deepdfa_tpu.cpg.validate import validate_corpus

    summary = dict(validate_corpus(cpgs.items()))
    if not drop_errors:
        return cpgs, summary
    bad = set(summary["error_graph_ids"])
    kept = {gid: cpg for gid, cpg in cpgs.items() if gid not in bad}
    return kept, summary


# ---------------------------------------------------------------------------
# extraction quarantine report

QUARANTINE_FILE = "quarantine.json"


def write_quarantine(out_dir: str | Path, report: dict) -> Path:
    """Persist an :class:`~deepdfa_tpu.resilience.ExtractionSupervisor`
    report (``{"restarts": int, "quarantined": [entry, ...]}``) next to the
    shard output, atomically — poison functions are *recorded*, never the
    reason a corpus build aborts. Returns the file path."""
    from deepdfa_tpu.resilience.journal import atomic_write_text

    path = Path(out_dir) / QUARANTINE_FILE
    atomic_write_text(path, json.dumps(report, indent=2, default=str))
    return path


def read_quarantine(out_dir: str | Path) -> dict:
    """The recorded quarantine report, or an empty one if the build had no
    poison functions (the file is only written when non-empty)."""
    path = Path(out_dir) / QUARANTINE_FILE
    if not path.exists():
        return {"restarts": 0, "quarantined": []}
    return json.loads(path.read_text())


# ---------------------------------------------------------------------------
# dataset class


class VulnDataset:
    """Partitioned function-level dataset with per-epoch rebalancing.

    Parity with ``BigVulDataset`` (``dclass.py:18-118``): filter → partition →
    ``idx2id``; ``epoch_ids`` re-draws the undersampled non-vul subset every
    epoch (seeded by (seed, epoch) — deterministic, unlike the reference's
    mutable ``RandomState``, but equally resampled-per-epoch).
    """

    def __init__(
        self,
        dsname: str = "bigvul",
        part: str = "train",
        seed: int = 0,
        sample: int = -1,
        sample_mode: bool = False,
        split: str = "fixed",
        undersample: str | float | None = None,
        oversample: float | None = None,
        check_file: bool = True,
        check_valid: bool = True,
        vulonly: bool = False,
        df: pd.DataFrame | None = None,
        splits: dict | None = None,
    ):
        self.part = part
        self.undersample = undersample
        self.oversample = oversample
        self.seed = seed
        if df is None:
            df = ds(dsname, sample=sample_mode)
        df = filter_dataset(
            df,
            dsname,
            check_file=check_file,
            check_valid=check_valid,
            vulonly=vulonly,
            load_code=True,
            sample=sample,
            sample_mode=sample_mode,
            seed=seed,
        )
        if not sample_mode:
            df = partition(df, part, dsname, split=split, seed=seed, splits=splits)
        self.df = df.reset_index(drop=True)
        self.idx2id = dict(zip(self.df.index, self.df.id.values))

    def vuln_lines(self, _id) -> dict[int, int]:
        """Removed (= vulnerable) line numbers for one function
        (``dclass.py:78-82``)."""
        removed = self.df[self.df.id == _id].removed.item()
        return {i: 1 for i in removed}

    def epoch_ids(self, epoch: int = 0, shuffle: bool = True) -> np.ndarray:
        """Example *ids* to visit this epoch (rebalanced, reshuffled)."""
        from deepdfa_tpu.data.sampler import epoch_indices

        idx = epoch_indices(
            self.df.vul.to_numpy(),
            undersample=self.undersample,
            oversample=self.oversample,
            seed=self.seed,
            epoch=epoch,
            shuffle=shuffle,
        )
        return self.df.id.to_numpy()[idx]

    def positive_weight(self) -> float:
        from deepdfa_tpu.data.sampler import positive_weight

        return positive_weight(self.df.vul.to_numpy())

    def __getitem__(self, idx: int) -> dict:
        return self.df.iloc[idx].to_dict()

    def __len__(self) -> int:
        return len(self.df)

    def __repr__(self) -> str:
        frac = round(float((self.df.vul == 1).mean()), 3) if len(self.df) else 0.0
        return f"VulnDataset(part={self.part}, n={len(self.df)}, vul%={frac})"
