"""Dense-adjacency batch layout: sparse GNNs on dense hardware.

Big-Vul functions are SMALL graphs (mean ~50 CFG nodes), so per-graph dense
adjacency is tiny — and on a TPU a batched ``[G,n,n] @ [G,n,d]`` matmul on
the MXU beats gather/scatter message passing that crawls through the VPU's
scatter path (the round-3 bench measured the segment-path GGNN at ~3% of
the chip's matmul ceiling; scatter, not matmul, bound). This module is the
data side of that trade: pack each graph into a fixed ``nodes_per_graph``
slot and materialise its adjacency as a dense ``[n, n]`` count matrix.

The pattern — turn sparse message passing into dense block matmuls sized to
the systolic array — follows the public "sparse GNNs on dense hardware"
line of work (arXiv:1906.11786); the layout here is per-graph block-diagonal
rather than one giant block-sparse matrix because CFGs are naturally tiny
and bucketed (replaces DGL's ragged ``dgl.batch``/SpMM pipeline the
reference uses, ``flow_gnn/ggnn.py:57-60``).

Semantics match :func:`deepdfa_tpu.data.graphs.batch_np` + segment
reductions exactly: ``adj[g, j, i]`` counts edges j→i within graph ``g``
(duplicate edges accumulate, matching duplicate contributions in
``segment_sum``); self-loops are expected in the edge lists (materialisation
adds them). Padding nodes have zero adjacency rows/columns and are excluded
from pooling by ``node_mask``.
"""

from __future__ import annotations

from typing import Iterator, NamedTuple, Sequence

import numpy as np

from deepdfa_tpu.data.graphs import Graph

__all__ = ["DenseBatch", "batch_dense", "DenseBatcher", "derive_dense_size",
           "derive_dense_sizes"]


class DenseBatch(NamedTuple):
    """Device-ready dense batch. All shapes static.

    node_feats: dict of ``[max_graphs, nodes_per_graph, ...]`` arrays,
    carried generically (any key present on the input graphs — including the
    ``_DFA_*`` static-analysis families — is padded and batched unchanged).
    adj: ``[max_graphs, n, n]`` — ``adj[g, j, i]`` = #edges j→i (compute
    dtype is chosen by the model; stored f32 to keep counts exact).
    node_mask: ``[max_graphs, n]`` bool. graph_mask: ``[max_graphs]`` bool.
    """

    node_feats: dict
    adj: np.ndarray
    node_mask: np.ndarray
    graph_mask: np.ndarray

    @property
    def max_graphs(self) -> int:
        return self.graph_mask.shape[0]

    @property
    def nodes_per_graph(self) -> int:
        return self.node_mask.shape[1]


def batch_dense(
    graphs: Sequence[Graph],
    max_graphs: int,
    nodes_per_graph: int,
    extra_feat_pad: dict[str, float] | None = None,
) -> DenseBatch:
    """Pack ``graphs`` (each with ``n_nodes <= nodes_per_graph``) into one
    dense batch. Unlike :func:`batch_np` no slots are reserved: padding
    nodes/graphs are inert (zero adjacency, masked out of pooling)."""
    n_real = len(graphs)
    if n_real > max_graphs:
        raise ValueError(f"{n_real} graphs > budget {max_graphs}")
    n = nodes_per_graph
    adj = np.zeros((max_graphs, n, n), np.float32)
    node_mask = np.zeros((max_graphs, n), bool)
    pad_values = extra_feat_pad or {}

    node_feats: dict[str, np.ndarray] = {}
    keys = graphs[0].node_feats.keys() if graphs else ()
    for key in keys:
        sample = graphs[0].node_feats[key]
        node_feats[key] = np.full(
            (max_graphs, n) + sample.shape[1:], pad_values.get(key, 0),
            dtype=sample.dtype,
        )

    for gi, g in enumerate(graphs):
        nn_ = g.n_nodes
        if nn_ > n:
            raise ValueError(
                f"graph gid={g.gid} has {nn_} nodes > nodes_per_graph={n}"
            )
        np.add.at(adj[gi], (g.senders, g.receivers), 1.0)
        node_mask[gi, :nn_] = True
        for key in keys:
            node_feats[key][gi, :nn_] = g.node_feats[key]

    graph_mask = np.arange(max_graphs) < n_real
    return DenseBatch(node_feats=node_feats, adj=adj, node_mask=node_mask,
                      graph_mask=graph_mask)


def derive_dense_size(graphs: Sequence[Graph], quantile: float = 0.99,
                      round_to: int = 8) -> int:
    """Per-graph node budget from the corpus size distribution: the
    ``quantile`` node count rounded up to ``round_to`` (graphs above it take
    the batcher's oversize route — collect/drop/raise)."""
    if not graphs:
        raise ValueError("empty corpus")
    sizes = np.array([g.n_nodes for g in graphs])
    q = float(np.quantile(sizes, quantile))
    return int(-(-max(q, 1.0) // round_to) * round_to)


def derive_dense_sizes(
    graphs: Sequence[Graph],
    quantiles: Sequence[float] | None = None,
    round_to: int = 8,
    k: int = 6,
    oversize_quantile: float = 0.99,
) -> list[int]:
    """Per-graph node budgets (one compiled shape each), chosen to MINIMISE
    total padded node slots.

    Slot cost scales n² in the adjacency matmuls, so a single p99 budget
    pads median graphs ~4× in FLOPs. Round 3 used a fixed {p50, p99}
    quantile pair (occupancy ≈ 0.49 on the bench corpus — VERDICT r04 #2
    flagged it); round 5 replaces the heuristic with the OPTIMAL ``k``-bucket
    split: an O(k·U²) DP over the (rounded) size histogram minimising
    ``Σ_g budget(g)``, i.e. maximising node-slot occupancy directly
    (measured on the bench corpus: 0.49 → 0.83 at the default k=6 with
    full batches; more shapes trade XLA compiles for occupancy, and past
    ~k=8 streaming-mode flush waste dominates). Graphs
    above the ``oversize_quantile`` budget keep taking the batcher's
    oversize route, exactly as before. ``quantiles`` (legacy) overrides the
    DP with the old behavior when passed explicitly.
    """
    if quantiles is not None:
        return sorted({derive_dense_size(graphs, q, round_to) for q in quantiles})
    if not graphs:
        raise ValueError("empty corpus")
    cap = derive_dense_size(graphs, oversize_quantile, round_to)
    rounded = np.array(sorted(
        int(-(-max(g.n_nodes, 1) // round_to) * round_to)
        for g in graphs
        if -(-max(g.n_nodes, 1) // round_to) * round_to <= cap
    ))
    cands = sorted(set(rounded.tolist()) | {cap})
    # prefix[i] = #graphs with rounded size <= cands[i]
    prefix = np.searchsorted(rounded, cands, side="right")
    U = len(cands)
    k = min(k, U)
    INF = float("inf")
    # dp[m][j]: min total slots covering all graphs <= cands[j] with m
    # buckets whose largest budget is cands[j]
    dp = [[INF] * U for _ in range(k + 1)]
    back = [[-1] * U for _ in range(k + 1)]
    for j in range(U):
        dp[1][j] = float(prefix[j] * cands[j])
    for m in range(2, k + 1):
        for j in range(m - 1, U):
            best, arg = dp[m - 1][j], -2  # fewer buckets is always legal
            for i in range(j):
                c = dp[m - 1][i] + float((prefix[j] - prefix[i]) * cands[j])
                if c < best:
                    best, arg = c, i
            dp[m][j] = best
            back[m][j] = arg
    # reconstruct from dp[k][U-1] (top bucket must be the cap so every
    # non-oversize graph fits)
    sizes = []
    m, j = k, U - 1
    while m >= 1 and j >= 0:
        sizes.append(cands[j])
        i = back[m][j] if m > 1 else -1
        if i == -2:  # same-j fewer-bucket fallthrough
            m -= 1
            continue
        j = i
        m -= 1
    return sorted(set(sizes))


class DenseBatcher:
    """Greedy fixed-shape packer for the dense layout: each graph goes to the
    smallest of ``sizes`` (per-graph node budgets; one compiled shape each)
    that fits, and full batches of ``max_graphs`` are emitted per size.

    Graphs over the largest size have three routes:

    - ``collect_oversize=True`` (how the trainer runs it): kept in
      ``oversize_graphs`` for the caller to score through the segment-layout
      forward (same parameter tree, parity-tested) — every graph in the
      corpus gets a prediction; nothing is silently dropped.
    - ``drop_oversize=True``: dropped and counted in ``n_dropped`` (bench
      subsetting only — a classifier must not evaluate this way).
    - otherwise: raise, matching :class:`deepdfa_tpu.data.graphs.GraphBatcher`.
    """

    def __init__(self, max_graphs: int, nodes_per_graph: int | Sequence[int],
                 drop_oversize: bool = True, collect_oversize: bool = False):
        sizes = ([nodes_per_graph] if isinstance(nodes_per_graph, int)
                 else sorted(nodes_per_graph))
        if max_graphs < 1 or not sizes or min(sizes) < 1:
            raise ValueError("max_graphs and every size must be >= 1")
        self.max_graphs = max_graphs
        self.sizes = sizes
        self.nodes_per_graph = sizes[-1]  # largest; single-size back-compat
        self.drop_oversize = drop_oversize
        self.collect_oversize = collect_oversize
        self.n_dropped = 0
        self.oversize_graphs: list[Graph] = []

    def _size_for(self, g: Graph) -> int | None:
        for s in self.sizes:
            if g.n_nodes <= s:
                return s
        return None

    def batches(
        self, graphs: Sequence[Graph], limit_per_size: int | None = None
    ) -> Iterator[DenseBatch]:
        """With ``limit_per_size``, emit at most that many FULL batches per
        size, skip routing graphs to already-full sizes (a [G,n,n] adjacency
        is several MB — packing batches only to discard them is real work),
        and stop entirely once every size is full. Partial batches are only
        flushed in the unlimited mode."""
        self.n_dropped = 0
        self.oversize_graphs = []
        pending: dict[int, list[Graph]] = {s: [] for s in self.sizes}
        emitted: dict[int, int] = {s: 0 for s in self.sizes}
        for g in graphs:
            s = self._size_for(g)
            if s is None:
                if self.collect_oversize:
                    self.oversize_graphs.append(g)
                    continue
                if self.drop_oversize:
                    self.n_dropped += 1
                    continue
                raise ValueError(
                    f"graph gid={g.gid} ({g.n_nodes} nodes) exceeds the "
                    f"largest dense size {self.sizes[-1]}"
                )
            if limit_per_size is not None and emitted[s] >= limit_per_size:
                continue
            pending[s].append(g)
            if len(pending[s]) == self.max_graphs:
                yield batch_dense(pending[s], self.max_graphs, s)
                pending[s] = []
                emitted[s] += 1
                if (limit_per_size is not None
                        and all(n >= limit_per_size for n in emitted.values())):
                    return
        if limit_per_size is None:
            for s, left in pending.items():
                if left:
                    yield batch_dense(left, self.max_graphs, s)

    def occupancy(self, batches: Sequence[DenseBatch]) -> dict[str, float]:
        """Fraction of node slots / graph slots holding real data,
        slot-weighted (batches of different shapes hold different slot
        counts — an unweighted per-batch mean would overstate packing)."""
        if not batches:
            return {"nodes": 0.0, "graphs": 0.0}
        node_full = sum(int(b.node_mask.sum()) for b in batches)
        node_slots = sum(b.node_mask.size for b in batches)
        graph_full = sum(int(b.graph_mask.sum()) for b in batches)
        graph_slots = sum(b.graph_mask.size for b in batches)
        return {"nodes": node_full / node_slots, "graphs": graph_full / graph_slots}
