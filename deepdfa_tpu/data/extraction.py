"""Streaming extraction pool: N supervised sessions + work-stealing deque.

:class:`~deepdfa_tpu.resilience.supervisor.ExtractionSupervisor` made a
single crash-prone session survivable; this generalizes it to an N-worker
pool so corpus extraction scales with workers instead of idling behind
one JVM. Each worker thread owns its OWN supervised session (spawn retry
with backoff, restart-on-failure, quarantine-on-repeat — invariant 4
semantics and the ``SESSION_ERRORS`` classification are exactly the
supervisor's, per worker), pulls from its own deque and *steals* from the
back of the longest other queue when it runs dry — one poison or slow
function stalls one worker, never the fleet.

Failure domains, narrowest first:

- an item-level error (``ValueError`` family, including
  :class:`ExtractionItemError` from a process-backed session) is one
  failure row — the caller's failure-file protocol;
- a session-level failure restarts that worker's session and retries the
  item (supervisor semantics); a poison item lands on the shared
  quarantine list after ``attempts_per_item`` tries;
- a crashed *worker* (the ``extract.worker_crash`` chaos point, or any
  unexpected worker-loop error) re-queues its in-flight item onto the
  shared overflow deque — processed exactly once by a surviving worker,
  never lost, never double-counted — and anything still in every queue
  after the threads join is drained inline on a recovery session, so
  :meth:`ExtractionPool.run` completes the corpus even if every worker
  dies.

Sessions need not be JVMs: :class:`ProcessSession` runs a module-level
extractor in a dedicated **spawned** child process, so CPU-bound native
extraction scales past the GIL with the same supervision story (a dead
child is a ``SESSION_ERROR``; the supervisor respawns it).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Sequence

from deepdfa_tpu.resilience import faults
from deepdfa_tpu.resilience.retry import RetryPolicy
from deepdfa_tpu.resilience.supervisor import (
    ExtractionSupervisor,
    QuarantinedError,
)

__all__ = [
    "ExtractionItemError",
    "ExtractionPool",
    "ExtractionResult",
    "ProcessSession",
]

logger = logging.getLogger("deepdfa_tpu")


class ExtractionItemError(ValueError):
    """The ITEM failed inside a session (malformed source, extractor
    rejection) — the caller's failure-row protocol, not a session fault."""


class _WorkerCrashed(BaseException):
    """Internal: tears down one worker thread; never crosses run()."""

    def __init__(self, worker_id: int):
        super().__init__(f"extraction worker {worker_id} crashed")
        self.worker_id = worker_id


@dataclass
class ExtractionResult:
    """One item's outcome, in input order. Exactly one of ``value`` /
    ``error`` is set; ``quarantined`` marks the error as invariant-4
    quarantine (the item is on :meth:`ExtractionPool.report`'s list)."""

    key: Any
    value: Any = None
    error: str | None = None
    worker: int = -1
    cache_hit: bool = False
    quarantined: bool = False


class ExtractionPool:
    """``run(items, fn)`` → per-item results through N supervised sessions.

    ``session_factory(worker_id)`` builds one session per worker (also
    accepts a zero-arg factory). ``fn(session, payload)`` is the per-item
    extraction. An optional :class:`~deepdfa_tpu.data.extract_cache.
    ExtractCache` short-circuits items whose ``cache_code(payload)``
    source text is already committed — a warm re-run of an unchanged
    corpus performs zero extractions.
    """

    def __init__(
        self,
        session_factory: Callable[..., Any],
        n_workers: int = 4,
        *,
        attempts_per_item: int = 2,
        spawn_policy: RetryPolicy | None = None,
        cache=None,
        cache_code: Callable[[Any], str] | None = None,
        sleep: Callable[[float], None] = time.sleep,
    ):
        if n_workers < 1:
            raise ValueError("n_workers must be >= 1")
        self.n_workers = int(n_workers)
        self._factory = session_factory
        self._attempts = attempts_per_item
        self._spawn_policy = spawn_policy or RetryPolicy(
            attempts=3, base_delay=1.0, max_delay=15.0)
        self._sleep = sleep
        self._cache = cache
        self._cache_code = cache_code or (lambda payload: payload)
        self._queues: list[deque] = [deque() for _ in range(self.n_workers)]
        self._overflow: deque = deque()  # re-queued in-flight items
        self._lock = threading.Lock()
        self._results: dict[int, ExtractionResult] = {}
        self._quarantine: list[dict] = []
        self._restarts = 0
        self._steals = 0
        self._requeued = 0
        self._crashed: list[int] = []
        self._cache_hits = 0
        self._extracted = 0

    # -- session plumbing ---------------------------------------------------
    def _make_session(self, worker_id: int):
        try:
            return self._factory(worker_id)
        except TypeError:
            return self._factory()

    def _supervisor(self, worker_id: int) -> ExtractionSupervisor:
        return ExtractionSupervisor(
            lambda: self._make_session(worker_id),
            spawn_policy=self._spawn_policy,
            attempts_per_item=self._attempts,
            sleep=self._sleep,
        )

    # -- the work deque -----------------------------------------------------
    def _next_task(self, worker_id: int):
        """Own queue first, the shared overflow next, then steal from the
        back of the longest other queue. None == no work anywhere."""
        own = self._queues[worker_id]
        try:
            return own.popleft()
        except IndexError:
            pass
        try:
            return self._overflow.popleft()
        except IndexError:
            pass
        victims = sorted(
            (i for i in range(self.n_workers) if i != worker_id),
            key=lambda i: -len(self._queues[i]))
        for i in victims:
            try:
                task = self._queues[i].pop()  # steal cold work from the back
            except IndexError:
                continue
            with self._lock:
                self._steals += 1
            return task
        return None

    def _requeue(self, task, worker_id: int) -> None:
        self._overflow.append(task)
        with self._lock:
            self._requeued += 1
        logger.warning(
            "extraction worker %d re-queued in-flight item %r", worker_id,
            task[1])

    # -- per-item processing ------------------------------------------------
    def _record(self, idx: int, result: ExtractionResult) -> None:
        with self._lock:
            if idx in self._results:  # double-count guard (chaos-pinned)
                raise RuntimeError(
                    f"item {idx} ({result.key!r}) processed twice — the "
                    "re-queue path double-counted an in-flight item")
            self._results[idx] = result

    def _process(self, worker_id: int, sup: ExtractionSupervisor,
                 task, fn) -> None:
        idx, key, payload = task
        if self._cache is not None:
            cache_key = self._cache.key(self._cache_code(payload))
            value = self._cache.get(cache_key)
            if value is not None:
                with self._lock:
                    self._cache_hits += 1
                self._record(idx, ExtractionResult(
                    key, value=value, worker=worker_id, cache_hit=True))
                return
        try:
            value = sup.run(key, lambda session: fn(session, payload))
        except QuarantinedError as exc:
            self._record(idx, ExtractionResult(
                key, error=f"Quarantined: {exc.reason}", worker=worker_id,
                quarantined=True))
            return
        except Exception as exc:  # noqa: BLE001 — failure-file protocol
            self._record(idx, ExtractionResult(
                key, error=f"{type(exc).__name__}: {exc}", worker=worker_id))
            return
        if self._cache is not None:
            self._cache.put(cache_key, value)
        with self._lock:
            self._extracted += 1
        self._record(idx, ExtractionResult(key, value=value, worker=worker_id))

    # -- worker lifecycle ---------------------------------------------------
    def _worker_loop(self, worker_id: int, sup: ExtractionSupervisor,
                     fn) -> None:
        while True:
            task = self._next_task(worker_id)
            if task is None:
                return
            if faults.fire("extract.worker_crash"):
                self._requeue(task, worker_id)
                raise _WorkerCrashed(worker_id)
            self._process(worker_id, sup, task, fn)

    def _worker(self, worker_id: int, fn) -> None:
        sup = self._supervisor(worker_id)
        try:
            self._worker_loop(worker_id, sup, fn)
        except _WorkerCrashed:
            with self._lock:
                self._crashed.append(worker_id)
            logger.warning("extraction worker %d crashed; its queue will "
                           "be stolen by survivors", worker_id)
        finally:
            self._absorb(sup)
            sup.close()

    def _absorb(self, sup: ExtractionSupervisor) -> None:
        with self._lock:
            self._restarts += sup.restarts
            self._quarantine.extend(sup.quarantine)

    # -- driver -------------------------------------------------------------
    def run(self, items: Sequence[tuple[Any, Any]], fn) -> list[ExtractionResult]:
        """Extract every ``(key, payload)`` item; returns one
        :class:`ExtractionResult` per item, in input order. Never raises
        for a failing item — a corpus build survives its functions."""
        items = list(items)
        for i, (key, payload) in enumerate(items):
            self._queues[i % self.n_workers].append((i, key, payload))
        threads = [
            threading.Thread(target=self._worker, args=(wid, fn),
                             name=f"extract-{wid}", daemon=True)
            for wid in range(self.n_workers)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # leftovers exist only when workers crashed with work still queued
        # (including the crash-requeued in-flight items): drain them on one
        # recovery session so the build still completes.
        leftovers = [task for q in (*self._queues, self._overflow)
                     for task in self._drain(q)]
        if leftovers:
            logger.warning("draining %d left-over item(s) after worker "
                           "crash(es) on a recovery session", len(leftovers))
            sup = self._supervisor(-1)
            try:
                for task in leftovers:
                    self._process(-1, sup, task, fn)
            finally:
                self._absorb(sup)
                sup.close()
        with self._lock:
            return [self._results[i] for i in range(len(items))]

    @staticmethod
    def _drain(q: deque) -> list:
        out = []
        while True:
            try:
                out.append(q.popleft())
            except IndexError:
                return out

    def report(self) -> dict:
        """Aggregate for the ingest summary: supervisor semantics (restarts
        + quarantine list) plus the pool's own accounting."""
        with self._lock:
            return {
                "workers": self.n_workers,
                "restarts": self._restarts,
                "quarantined": list(self._quarantine),
                "steals": self._steals,
                "requeued": self._requeued,
                "crashed_workers": list(self._crashed),
                "cache_hits": self._cache_hits,
                "extracted": self._extracted,
            }


# ---------------------------------------------------------------------------
# process-backed sessions: CPU-bound extraction past the GIL


def _process_session_main(conn, extractor_ref: str) -> None:
    """Child loop: resolve ``module:function`` and serve items until EOF.
    Item failures are replied (not raised) — they must not kill the
    session; only a genuinely dead child implicates it."""
    import importlib

    try:
        mod_name, _, fn_name = extractor_ref.partition(":")
        fn = getattr(importlib.import_module(mod_name), fn_name)
    except Exception as exc:  # noqa: BLE001 — reported to the parent
        try:
            conn.send(("spawn_error", f"{type(exc).__name__}: {exc}"))
        finally:
            conn.close()
        return
    conn.send(("ready", None))
    while True:
        try:
            kind, payload = conn.recv()
        except (EOFError, OSError):
            return
        if kind == "stop":
            conn.close()
            return
        try:
            conn.send(("ok", fn(payload)))
        except Exception as exc:  # noqa: BLE001 — item error, session lives
            conn.send(("err", f"{type(exc).__name__}: {exc}"))


class ProcessSession:
    """An extraction session whose extractor runs in a dedicated spawned
    child process. ``extractor`` is a ``"module:function"`` reference
    resolved IN THE CHILD (spawn-safe; fork after jax init can deadlock).
    A dead/hung child raises ``SESSION_ERRORS`` members, so an
    :class:`~deepdfa_tpu.resilience.supervisor.ExtractionSupervisor`
    restarts it exactly like a dead JVM; extractor-level failures raise
    :class:`ExtractionItemError` and leave the session alive."""

    def __init__(self, extractor: str, *, timeout_s: float = 120.0,
                 spawn_timeout_s: float = 120.0):
        import multiprocessing

        self.timeout_s = timeout_s
        ctx = multiprocessing.get_context("spawn")
        self._conn, child = ctx.Pipe()
        self._proc = ctx.Process(
            target=_process_session_main, args=(child, extractor), daemon=True)
        self._proc.start()
        child.close()
        if not self._conn.poll(spawn_timeout_s):
            self.close()
            raise TimeoutError(
                f"process session did not report ready in {spawn_timeout_s}s")
        try:
            kind, detail = self._conn.recv()
        except (EOFError, OSError) as exc:
            self.close()
            raise RuntimeError("process session died during spawn") from exc
        if kind != "ready":
            self.close()
            raise RuntimeError(f"process session failed to spawn: {detail}")

    def extract(self, payload, timeout_s: float | None = None):
        timeout_s = self.timeout_s if timeout_s is None else timeout_s
        try:
            self._conn.send(("item", payload))
        except (OSError, ValueError) as exc:
            raise RuntimeError(f"process session pipe is dead: {exc}") from exc
        if not self._conn.poll(timeout_s):
            raise TimeoutError(
                f"process session gave no reply within {timeout_s}s")
        try:
            kind, out = self._conn.recv()
        except (EOFError, OSError) as exc:
            raise RuntimeError("process session died mid-item") from exc
        if kind == "ok":
            return out
        raise ExtractionItemError(out)

    def close(self) -> None:
        try:
            self._conn.send(("stop", None))
        except (OSError, ValueError):
            pass
        self._conn.close()
        self._proc.join(timeout=2.0)
        if self._proc.is_alive():
            self._proc.terminate()
            self._proc.join(timeout=2.0)
