"""deepdfa_tpu — a TPU-native dataflow-analysis-guided vulnerability-detection framework.

Brand-new implementation of the capabilities of aidanby/DeepDFA (ICSE'24 line of
work), designed for TPUs: JAX/XLA for compute, GSPMD/`jax.sharding` for scale,
Flax for modules, a host-side columnar CPG pipeline, and a C++ worklist solver
for exact reaching definitions.

Layer map (ours; reference layers cited in each module's docstring):

- :mod:`deepdfa_tpu.utils`     — storage layout, hashing, parallel map, seeding.
- :mod:`deepdfa_tpu.config`    — typed configuration (replaces the reference's
  feat-string DSL + layered YAML; see ``DDFA/code_gnn/main_cli.py:73-99``).
- :mod:`deepdfa_tpu.cpg`       — code-property-graph toolchain: Joern JSON
  ingestion, a native pycparser-based C frontend, reaching-definitions solvers.
- :mod:`deepdfa_tpu.data`      — datasets, vocab building, graph batching into
  fixed-shape padded :class:`~deepdfa_tpu.data.graphs.BatchedGraphs`.
- :mod:`deepdfa_tpu.models`    — Flax GGNN, fusion heads, Llama-family LLM.
- :mod:`deepdfa_tpu.ops`       — segment ops, differentiable set-union ops,
  attention (incl. ring attention), Pallas kernels.
- :mod:`deepdfa_tpu.parallel`  — mesh construction, sharding rules, collectives.
- :mod:`deepdfa_tpu.train`     — train loops, metrics, checkpoints, profiling.
"""

__version__ = "0.1.0"

from deepdfa_tpu.utils import (  # noqa: F401
    cache_dir,
    dfmp,
    external_dir,
    get_run_id,
    hashstr,
    processed_dir,
    seed_all,
    storage_dir,
)
