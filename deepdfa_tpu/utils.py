"""Storage layout, hashing, host-side parallel map, deterministic seeding.

Reference surface covered: ``DDFA/sastvd/__init__.py:37-250`` (storage_dir /
external_dir / processed_dir / cache_dir, get_run_id, hashstr, dfmp) minus the
Singularity wrapper, which has no TPU-era role.
"""

from __future__ import annotations

import datetime
import hashlib
import multiprocessing
import os
import random
from pathlib import Path
from typing import Any, Callable, Iterable, Sequence

import numpy as np

__all__ = [
    "project_dir",
    "storage_dir",
    "external_dir",
    "interim_dir",
    "processed_dir",
    "cache_dir",
    "get_dir",
    "get_run_id",
    "hashstr",
    "dfmp",
    "chunks",
    "seed_all",
    "debug_nans",
]


def project_dir() -> Path:
    """Repo root (directory containing the ``deepdfa_tpu`` package)."""
    return Path(__file__).resolve().parent.parent


def storage_dir() -> Path:
    """Storage root; override with env ``DEEPDFA_STORAGE``.

    Mirrors the reference's ``storage_dir()`` + ``SINGSTORAGE`` override
    (``sastvd/__init__.py:42-58``).
    """
    override = os.environ.get("DEEPDFA_STORAGE")
    path = Path(override) if override else project_dir() / "storage"
    path.mkdir(exist_ok=True, parents=True)
    return path


def _sub(name: str) -> Path:
    path = storage_dir() / name
    path.mkdir(exist_ok=True, parents=True)
    return path


def external_dir() -> Path:
    """Downloaded / externally produced artifacts (raw CSVs, Joern outputs)."""
    return _sub("external")


def interim_dir() -> Path:
    """Intermediate artifacts."""
    return _sub("interim")


def processed_dir() -> Path:
    """Fully processed, training-ready artifacts."""
    return _sub("processed")


def cache_dir() -> Path:
    """Memoisation caches; safe to delete."""
    return _sub("cache")


def get_dir(path: Path | str) -> Path:
    """mkdir -p and return. ``exist_ok`` makes this safe under concurrency
    (the reference documents the same rationale, ``sastvd/__init__.py:26-34``)."""
    path = Path(path)
    path.mkdir(exist_ok=True, parents=True)
    return path


def get_run_id(args: Sequence[str] | None = None) -> str:
    """Timestamped unique run id, e.g. ``202607290755_1a2b3c_msg``.

    Parity with ``sastvd/__init__.py:85-103`` (timestamp + short random hex +
    optional slug), reproducible when ``seed_all`` was called.
    """
    stamp = datetime.datetime.now().strftime("%Y%m%d%H%M%S")
    nonce = "%06x" % random.randrange(16**6)
    slug = "_".join(str(a) for a in args) if args else ""
    return f"{stamp}_{nonce}" + (f"_{slug}" if slug else "")


def hashstr(s: str) -> int:
    """Stable small-int hash of a string: sha1 mod 1e8.

    Same construction as the reference (``sastvd/__init__.py:188-192``) so
    hash-derived artifacts are comparable across frameworks.
    """
    return int(hashlib.sha1(s.encode("utf-8")).hexdigest(), 16) % (10**8)


def chunks(seq: Sequence[Any], n: int) -> Iterable[Sequence[Any]]:
    """Yield successive n-sized chunks."""
    for i in range(0, len(seq), n):
        yield seq[i : i + n]


def dfmp(
    df,
    function: Callable[[Any], Any],
    columns: str | Sequence[str] | None = None,
    ordr: bool = True,
    workers: int = 6,
    cs: int = 10,
    desc: str = "Run: ",
) -> list:
    """Parallel map over a DataFrame's records (host-side CPU fan-out).

    Parity with ``sastvd/__init__.py:195-244``: items are full records
    (dicts), a single column's values, or tuples of the selected columns;
    ordered (``imap``) or unordered (``imap_unordered``); chunked; tqdm'd.
    Falls back to a serial map when ``workers <= 1`` (useful in tests and on
    single-core hosts).

    Workers come from an explicit **spawn** context: the default fork start
    method after a jax import can deadlock children on inherited runtime
    locks, and ``maxtasksperchild`` recycles workers so one leaky native
    extraction cannot grow a worker process unboundedly. A worker exception
    propagates to the caller (the pool survives and is torn down cleanly).
    """
    import tqdm

    if columns is None:
        items = df.to_dict("records")
    elif isinstance(columns, str):
        items = df[columns].tolist()
    else:
        items = list(df[list(columns)].itertuples(index=False, name=None))

    if workers <= 1:
        return [function(i) for i in tqdm.tqdm(items, total=len(items), desc=desc)]

    mapper = lambda pool: pool.imap(function, items, cs) if ordr else pool.imap_unordered(function, items, cs)
    ctx = multiprocessing.get_context("spawn")
    with ctx.Pool(processes=workers, maxtasksperchild=64) as pool:
        return list(tqdm.tqdm(mapper(pool), total=len(items), desc=desc))


def seed_all(seed: int = 0) -> None:
    """Seed every host-side RNG we use (random, numpy).

    JAX randomness is functional (explicit ``jax.random.key``); training code
    derives keys from the config seed, so this only needs to cover host RNGs.
    Parity with ``code_gnn/globals.py:26-33``.
    """
    random.seed(seed)
    np.random.seed(seed)


def debug_nans(enable: bool = True) -> None:
    """TPU-era analogue of the reference trainer's ``detect_anomaly: true``
    (``configs/config_default.yaml:41``): make XLA error out on NaNs."""
    import jax

    jax.config.update("jax_debug_nans", enable)
