"""Score raw C source with a trained checkpoint — `deepdfa-tpu predict`.

The reference has no single-command scan surface: scoring new code means
re-running its preprocessing stack into shards and pointing the test
harness at them (`DDFA/scripts/preprocess.sh` → `main_cli.py test`). Here
the hermetic frontend (:mod:`deepdfa_tpu.cpg.frontend`), the
abstract-dataflow features encoded with the TRAINING vocabulary
(:mod:`deepdfa_tpu.data.vocab` — predict must never rebuild a vocabulary
from the code being scored), and the trained GGNN compose into one call:
C source in, per-function vulnerability probability plus ranked suspicious
statements out.

Statement ranking: for ``label_style="node"`` checkpoints the per-node
sigmoid scores rank statements directly (the IVDetect top-k protocol,
reference contract ``DDFA/sastvd/helpers/evaluate.py:262-322``). For the
flagship graph-label model the DEFAULT signal is **occlusion saliency**
(:func:`occlusion_saliency` — Δ probability when each statement's
dataflow features are masked; 12/12 top-1 on the round-5 localization
study, BASELINE.md); the readout's attention gate
(``GlobalAttentionPooling``, reference
``code_gnn/models/flow_gnn/ggnn.py:66-68``) remains available as the
1-forward cheap mode (``--saliency gate``) but localizes poorly (0/12
top-1 in the same study).
"""

from __future__ import annotations

from pathlib import Path
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deepdfa_tpu.config import ExperimentConfig
from deepdfa_tpu.data.graphs import _round_up, batch_np
from deepdfa_tpu.data.vocab import Vocabulary
from deepdfa_tpu.pipeline import all_subkeys as _all_subkeys  # noqa: F401 — API compat
from deepdfa_tpu.pipeline import encode_cpg as _encode  # noqa: F401 — API compat
from deepdfa_tpu.pipeline import encode_source, load_vocabs

__all__ = [
    "load_vocabs", "make_scorer", "predict_source", "predict_paths",
    "collect_sources",
]


def make_scorer(model, label_style: str) -> Callable:
    """One jitted ``(params, batch) -> (fn_prob[max_graphs],
    node_saliency[max_nodes])`` scorer. Built once per scan so every
    function of the same padded batch shape reuses one XLA executable;
    unsupported checkpoints fail HERE with a clear message, not as a
    KeyError deep inside scoring."""
    if getattr(model, "cfg", None) is not None and model.cfg.encoder_mode:
        raise ValueError(
            "predict needs a classifier head; encoder_mode checkpoints "
            "return pooled embeddings (use the joint-fusion test path)"
        )
    if label_style == "node":
        @jax.jit
        def score(params, batch):
            node_p = jax.nn.sigmoid(model.apply({"params": params}, batch))
            # function score = max node probability over the real nodes
            neg = jnp.full_like(node_p, -jnp.inf)
            masked = jnp.where(batch.node_mask, node_p, neg)
            fn_p = jax.ops.segment_max(masked, batch.node_gidx,
                                       num_segments=batch.max_graphs)
            return fn_p, node_p
        return score
    if label_style != "graph":
        raise ValueError(
            f"predict supports label_style 'graph' or 'node', not "
            f"{label_style!r} (dataflow-solution checkpoints score RD bits, "
            "not vulnerability)"
        )

    @jax.jit
    def score(params, batch):
        logits, mods = model.apply({"params": params}, batch,
                                   mutable=["intermediates"])
        gate = mods["intermediates"]["pooling"]["gate_weights"][0]
        return jax.nn.sigmoid(logits), gate
    return score


def occlusion_saliency(
    scorer: Callable, params, g, n_real: int, chunk: int = 16,
    full_p: float | None = None,
) -> np.ndarray:
    """Per-node evidence contribution: Δ function probability when that
    node's abstract-dataflow features are masked to not-a-def (id 0).

    Measured head-to-head on unseen vulnerable demo functions (round 5,
    BASELINE.md): the attention gate ranks the defective definition top-1
    in 0/12 (it concentrates on loop headers — attention-as-explanation's
    known failure mode); occlusion ranks it top-1 in 12/12. Cost: one
    scorer call per ``chunk`` masked copies instead of one per function —
    the copies ride ONE padded batch, and the tail chunk is padded with
    unmasked copies so every chunk of a given function size shares a
    compiled shape.
    """
    import dataclasses

    if full_p is None:  # predict_source already has it; standalone callers don't
        full_b = batch_np([g], 2, _round_up(g.n_nodes + 2),
                          max(_round_up(g.n_edges), 128))
        fp, _ = scorer(params, jax.tree.map(jnp.asarray, full_b))
        full_p = float(np.asarray(fp, np.float32)[0])

    sal = np.zeros(n_real, np.float32)
    abs_keys = [k for k in g.node_feats if k.startswith("_ABS_DATAFLOW")]
    for start in range(0, n_real, chunk):
        idxs = list(range(start, min(start + chunk, n_real)))
        copies = []
        for i in idxs:
            nf = {k: (v.copy() if k in abs_keys else v)
                  for k, v in g.node_feats.items()}
            for k in abs_keys:
                nf[k][i] = 0
            copies.append(dataclasses.replace(g, node_feats=nf))
        copies += [g] * (chunk - len(idxs))  # shape-stable tail padding
        mb = batch_np(
            copies, chunk + 1, _round_up(chunk * g.n_nodes + 2),
            max(_round_up(chunk * g.n_edges), 128),
        )
        probs, _ = scorer(params, jax.tree.map(jnp.asarray, mb))
        probs = np.asarray(probs, np.float32)
        for j, i in enumerate(idxs):
            sal[i] = full_p - probs[j]
    return sal


def predict_source(
    code: str,
    *,
    scorer: Callable,
    params,
    vocabs: dict[str, Vocabulary],
    top_k: int = 5,
    name: str = "<source>",
    saliency: str = "occlusion",
    label_style: str = "graph",
) -> list[dict]:
    """Score every function in ``code``; one result dict per function.

    ``saliency`` (graph-label checkpoints): ``"occlusion"`` (default —
    per-statement evidence drop, see :func:`occlusion_saliency`) or
    ``"gate"`` (the readout's attention weights; one forward, cheaper,
    much weaker localization). Node-label checkpoints always rank by the
    per-node probabilities.

    Functions are scored one per batch with budget shapes rounded up
    (:func:`_round_up`), so the jitted ``scorer`` compiles once per size
    bucket and similarly-sized functions reuse the executable.
    """
    if saliency not in ("occlusion", "gate"):
        raise ValueError(f"saliency must be 'occlusion' or 'gate', "
                         f"not {saliency!r}")
    results = []
    # the shared pipeline (deepdfa_tpu/pipeline.py) — same path serve takes
    for enc in encode_source(code, vocabs):
        fname, g, node_ids, cpg = enc.name, enc.graph, enc.node_ids, enc.cpg
        if g is None:
            results.append({"function": fname, "file": name,
                            "error": enc.error})
            continue
        batch = batch_np(
            [g], 2, _round_up(g.n_nodes + 2),
            max(_round_up(g.n_edges), 128),
        )
        dev = jax.tree.map(jnp.asarray, batch)
        fn_p, node_sal = scorer(params, dev)
        prob = float(np.asarray(fn_p, np.float32)[0])
        used = saliency
        if label_style == "node":
            used = "node_probability"
            sal = np.asarray(node_sal, np.float32)[: len(node_ids)]
        elif saliency == "occlusion":
            sal = occlusion_saliency(scorer, params, g, len(node_ids),
                                     full_p=prob)
        else:
            sal = np.asarray(node_sal, np.float32)[: len(node_ids)]
        order = np.argsort(-sal)[: max(top_k, 0)]
        statements = [
            {
                "line": cpg.nodes[node_ids[i]].line,
                "code": cpg.nodes[node_ids[i]].code,
                "weight": round(float(sal[i]), 6),
            }
            for i in order
        ]
        results.append({
            "function": fname,
            "file": name,
            "vulnerable_probability": round(prob, 6),
            "saliency": used,
            "top_statements": statements,
        })
    return results


def collect_sources(paths: Sequence[str | Path]) -> list[tuple[str, str]]:
    """(display name, source text) for each file; directories recurse over
    ``*.c`` only — the frontend is a C11 parser (pycparser), so globbing
    C++ or declaration-only headers would guarantee an error row per file.
    An explicit FILE path of any extension is still honored (the caller
    asked for that exact file). Missing paths raise."""
    out: list[tuple[str, str]] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            files = sorted(p.rglob("*.c"))
        elif p.exists():
            files = [p]
        else:
            raise FileNotFoundError(p)
        out.extend((str(f), f.read_text(errors="replace")) for f in files)
    return out


def predict_paths(
    paths: Sequence[str | Path],
    *,
    cfg: ExperimentConfig,
    model,
    params,
    vocabs: dict[str, Vocabulary],
    top_k: int = 5,
    saliency: str = "occlusion",
) -> dict:
    """Scan files/dirs. Returns ``{results, n_scored, n_errors}`` —
    ``n_scored`` counts successfully scored FUNCTIONS; error entries
    (unparseable file, function with no CFG) are separate, since one
    unparseable file says nothing about how many functions it held.

    Frontend failures are per-file results with an ``error`` field — a
    scan must report unparseable code, not die on it (mirrors the
    preprocess pipeline's ``failed_frontend.txt`` policy).
    """
    from deepdfa_tpu.cpg.frontend import FrontendError

    any_voc = next(iter(vocabs.values()))
    if any_voc.input_dim != cfg.input_dim:
        raise ValueError(
            f"vocab input_dim {any_voc.input_dim} != config input_dim "
            f"{cfg.input_dim} — the checkpoint and the shard dir disagree"
        )
    scorer = make_scorer(model, cfg.model.label_style)
    results: list[dict] = []
    for p in paths:
        found = collect_sources([p])
        if not found:
            # a .c-less directory must not read as a clean scan of nothing
            results.append({
                "file": str(p),
                "error": "directory contains no .c files "
                         "(the frontend parses C11 only)",
            })
            continue
        for name, code in found:
            try:
                results.extend(predict_source(
                    code, scorer=scorer, params=params, vocabs=vocabs,
                    top_k=top_k, name=name, saliency=saliency,
                    label_style=cfg.model.label_style,
                ))
            except (FrontendError, SyntaxError, ValueError) as e:
                results.append({"file": name,
                                "error": f"{type(e).__name__}: {e}"})
    n_err = sum(1 for r in results if "error" in r)
    return {
        "results": results,
        "n_scored": len(results) - n_err,
        "n_errors": n_err,
    }
