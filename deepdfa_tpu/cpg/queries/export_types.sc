// deepdfa-tpu Joern query: resolve a type name to its leaf member types.
//
// Capability parity with the reference's get_type.sc (struct members are
// flattened to leaf/external type names so the abstract-dataflow `datatype`
// feature can hash composite types consistently); reimplemented on the same
// public Joern traversal API.
//
// Run: joern --script export_types.sc --params "typename=my_struct,filename=f.c"
// Output: {filename}.types.{typename}.json — JSON array of leaf type names.

import better.files.File
import scala.collection.mutable

def resolveAlias(tn: String): Traversal[TypeDecl] = {
  val aliases = cpg.typeDecl.name(tn).aliasTypeFullName.dedup.l
  aliases.headOption match {
    case Some(target) if target.startsWith("anonymous_type_") =>
      // anonymous aliases index into the file's unnamed decls by order
      val idx = target.stripPrefix("anonymous_type_").toInt
      cpg.typeDecl
        .name("")
        .filename(cpg.typeDecl.name(tn).filename.head)
        .sortBy(_.order)
        .drop(idx)
        .take(1)
    case Some(target) => cpg.typeDecl.name(target)
    case None         => cpg.typeDecl.name(tn)
  }
}

def leafTypes(decls: List[TypeDecl], seen: mutable.HashSet[String]): List[String] = {
  seen ++= decls.map(_.name)
  val external = decls.filter(_.isExternal).map(_.name)
  val members  = decls.flatMap(_.member.typeFullName.l).filterNot(seen)
  if (members.isEmpty) external ::: decls.map(_.name)
  else {
    seen ++= members
    external ::: members
      .flatMap(m => leafTypes(resolveAlias(m).l, seen))
      .distinct
  }
}

@main def exec(typename: String, filename: String) = {
  val binFile = File(filename + ".cpg.bin")
  if (binFile.exists) { importCpg(binFile.toString) } else { importCode(filename) }
  val leaves = leafTypes(resolveAlias(typename).l, mutable.HashSet[String]())
  val out = leaves.distinct.map(s => "\"" + s.replace("\"", "\\\"") + "\"")
  File(s"$filename.types.$typename.json").overwrite(out.mkString("[", ",", "]"))
  delete
}
