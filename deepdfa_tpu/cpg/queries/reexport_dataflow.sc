// deepdfa-tpu summary-cached dataflow RE-export (capability parity with
// DDFA/storage/external/get_dataflow_output.sc:26-75, reimplemented):
// re-run the reaching-definitions solver over an ALREADY-IMPORTED CPG
// ({filename}.cpg.bin, written by export_func_graph.sc) without
// re-extracting the source, and (re)write {filename}.dataflow.json.
//
// Cache contract: if {filename}.dataflow.summary.json exists and cache=true
// the run is a no-op (the reference's summary-cache key). On a successful
// re-solve this script ALSO writes that summary marker (method count +
// per-method solved-node counts) — the reference checks the marker but
// never writes it, leaving its cache permanently cold; writing it here is
// the evident intent.
//
// Run (batch):       joern --script reexport_dataflow.sc --params filename=f.c
// Run (interactive): via deepdfa_tpu.cpg.joern_session.JoernSession.run_script
// Native equivalent: deepdfa_tpu.cpg.joern.reexport_dataflow (no JVM).
//
// Tested against joern 1.1.x (the dataflowengineoss reaching-def API).

import better.files.File
import io.joern.dataflowengineoss.passes.reachingdef.{
  DataFlowSolver,
  ReachingDefFlowGraph,
  ReachingDefProblem,
  ReachingDefTransferFunction
}

def q(s: String): String = {
  val b = new StringBuilder("\"")
  s.foreach {
    case '"'  => b.append("\\\"")
    case '\\' => b.append("\\\\")
    case '\n' => b.append("\\n")
    case '\r' => b.append("\\r")
    case '\t' => b.append("\\t")
    case c if c < ' ' => b.append(f"\\u${c.toInt}%04x")
    case c    => b.append(c)
  }
  b.append("\"").toString
}

def jval(v: Any): String = v match {
  case null               => "null"
  case s: String          => q(s)
  case b: Boolean         => b.toString
  case i: Int             => i.toString
  case l: Long            => l.toString
  case d: Double          => d.toString
  case seq: Seq[_]        => seq.map(jval).mkString("[", ",", "]")
  case m: Map[_, _]       =>
    m.map { case (k, x) => q(k.toString) + ":" + jval(x) }.mkString("{", ",", "}")
  case other              => q(other.toString)
}

@main def exec(filename: String, cache: Boolean = true) = {
  val summaryFile = File(filename + ".dataflow.summary.json")
  if (summaryFile.exists && cache) {
    println(s"result is cached $filename")
  } else {
    try {
      val binFile = File(filename + ".cpg.bin")
      if (binFile.exists) {
        println(s"Loading CPG from $binFile")
        importCpg(binFile.toString)
      } else {
        println(s"No cached CPG; importing code $filename")
        importCode(filename)
      }

      val perMethod = cpg.method
        .filter(m => m.filename != "<empty>" && m.name != "<global>")
        .map { m =>
          val problem  = ReachingDefProblem.create(m)
          val solution = new DataFlowSolver().calculateMopSolutionForwards(problem)
          val tf       = problem.transferFunction.asInstanceOf[ReachingDefTransferFunction]
          val num2node = problem.flowGraph.asInstanceOf[ReachingDefFlowGraph].numberToNode
          def sets(raw: Map[_ <: AnyRef, Set[Int]]): Map[String, Seq[Long]] =
            raw.map { case (node, bits) =>
              val id = node.getClass.getMethod("id").invoke(node).toString
              id -> bits.toSeq.sorted.map(num2node).map(_.id)
            }.toMap
          m.name -> Map(
            "problem.gen"  -> sets(tf.gen),
            "problem.kill" -> sets(tf.kill),
            "solution.in"  -> sets(solution.in),
            "solution.out" -> sets(solution.out)
          )
        }
        .toMap

      File(filename + ".dataflow.json").overwrite(jval(perMethod))
      summaryFile.overwrite(jval(Map(
        "methods" -> perMethod.size,
        "solved_nodes" -> perMethod.map { case (k, v) =>
          k -> v("solution.in").size
        }
      )))
      println("Done re-exporting dataflow")
    } finally {
      try { delete } catch {
        case e: RuntimeException => println(s"Error deleting project: ${e.getMessage}")
      }
    }
  }
}
