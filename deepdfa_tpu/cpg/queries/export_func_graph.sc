// deepdfa-tpu Joern export query (CPG + reaching-definitions artifacts).
//
// Artifact contract — consumed by deepdfa_tpu/cpg/joern.py readers; same
// on-disk shapes as the reference pipeline it replaces (capability parity
// with DDFA/storage/external/get_func_graph.sc:26-75, reimplemented):
//
//   {filename}.nodes.json    array of node property maps
//   {filename}.edges.json    array of [inNodeId, outNodeId, label, VARIABLE]
//   {filename}.dataflow.json {method: {"problem.gen"/"problem.kill"/
//                             "solution.in"/"solution.out": {nodeId: [defIds]}}}
//   {filename}.cpg.bin       binary CPG (reused on re-runs: idempotent)
//
// Run (batch):       joern --script export_func_graph.sc --params filename=f.c
// Run (interactive): via deepdfa_tpu.cpg.joern_session.JoernSession.run_script
//
// Tested against joern 1.1.x (the dataflowengineoss reaching-def API).

import better.files.File
import io.joern.dataflowengineoss.passes.reachingdef.{
  DataFlowSolver,
  ReachingDefFlowGraph,
  ReachingDefProblem,
  ReachingDefTransferFunction
}

// Minimal JSON writer with proper string escaping (the artifact files hold
// raw C source in `code` properties — quotes/backslashes/newlines included).
def q(s: String): String = {
  val b = new StringBuilder("\"")
  s.foreach {
    case '"'  => b.append("\\\"")
    case '\\' => b.append("\\\\")
    case '\n' => b.append("\\n")
    case '\r' => b.append("\\r")
    case '\t' => b.append("\\t")
    case c if c < ' ' => b.append(f"\\u${c.toInt}%04x")
    case c    => b.append(c)
  }
  b.append("\"").toString
}

def jval(v: Any): String = v match {
  case null               => "null"
  case s: String          => q(s)
  case b: Boolean         => b.toString
  case i: Int             => i.toString
  case l: Long            => l.toString
  case d: Double          => d.toString
  case seq: Seq[_]        => seq.map(jval).mkString("[", ",", "]")
  case m: Map[_, _]       =>
    m.map { case (k, x) => q(k.toString) + ":" + jval(x) }.mkString("{", ",", "}")
  case other              => q(other.toString)
}

def rdSolutionJson(): String = {
  val perMethod = cpg.method
    .filter(m => m.filename != "<empty>" && m.name != "<global>")
    .map { m =>
      val problem  = ReachingDefProblem.create(m)
      val solution = new DataFlowSolver().calculateMopSolutionForwards(problem)
      val tf       = problem.transferFunction.asInstanceOf[ReachingDefTransferFunction]
      val num2node = problem.flowGraph.asInstanceOf[ReachingDefFlowGraph].numberToNode
      def sets(raw: Map[_ <: AnyRef, Set[Int]]): Map[String, Seq[Long]] =
        raw.map { case (node, bits) =>
          val id = node.getClass.getMethod("id").invoke(node).toString
          id -> bits.toSeq.sorted.map(num2node).map(_.id)
        }.toMap
      m.name -> Map(
        "problem.gen"  -> sets(tf.gen),
        "problem.kill" -> sets(tf.kill),
        "solution.in"  -> sets(solution.in),
        "solution.out" -> sets(solution.out)
      )
    }
    .toMap
  jval(perMethod)
}

@main def exec(
    filename: String,
    runOssDataflow: Boolean = true,
    exportJson: Boolean = true,
    exportCpg: Boolean = true,
    exportDataflow: Boolean = true,
    deleteAfter: Boolean = true
) = {
  val binFile = File(filename + ".cpg.bin")
  if (binFile.exists) {
    importCpg(binFile.toString)
  } else {
    importCode(filename)
    if (runOssDataflow) { run.ossdataflow }
  }

  if (exportCpg && !binFile.exists) {
    save
    File(project.path + "/cpg.bin").copyTo(binFile, overwrite = true)
  }

  if (exportJson) {
    val nodesOut = File(filename + ".nodes.json")
    val edgesOut = File(filename + ".edges.json")
    if (!nodesOut.exists || !edgesOut.exists) {
      val edgeRows = cpg.graph.E
        .map(e =>
          Seq(e.inNode.id, e.outNode.id, e.label, e.propertiesMap.get("VARIABLE"))
        )
        .toSeq
      edgesOut.overwrite(jval(edgeRows))
      val nodeRows = cpg.graph.V
        .map(v => v.propertiesMap.asScala.toMap ++ Map("id" -> v.id, "_label" -> v.label))
        .toSeq
      nodesOut.overwrite(jval(nodeRows))
    }
  }

  if (exportDataflow) {
    val dfOut = File(filename + ".dataflow.json")
    if (!dfOut.exists) { dfOut.overwrite(rdSolutionJson()) }
  }

  if (deleteAfter) { delete }
}
