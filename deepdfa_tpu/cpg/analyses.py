"""Generic monotone bit-vector dataflow framework + the analysis suite.

The reaching-definitions machinery this grew out of (``cpg/dataflow.py``)
hardcoded one analysis into three solvers. Here the analysis is *data*: a
:class:`Problem` declares ``(direction, meet, gen, kill)`` over a CPG's CFG
and any of the three backends solves it —

1. :func:`solve_sets`   — reference-shaped Python sets worklist;
2. :func:`solve_bitvec` — NumPy bit-matrix worklist (facts as bit positions);
3. :func:`solve_native` — C++ CSR worklist (``native/dfa_solver.cpp``) via
   ctypes, falling back to :func:`solve_bitvec` (with one warning) on hosts
   without a C++ toolchain.

Transfer function is the classic gen/kill form ``out = gen ∪ (in − kill)``.
``direction="backward"`` runs the same engine on the reversed CFG and swaps
the returned sets so :attr:`Solution.in_facts` is always the program-order
*entry* state (for liveness: ``in_facts = live_in``, ``out_facts =
live_out``). ``meet="may"`` is union (⊥ = ∅ start); ``meet="must"`` is
intersection (TOP start, boundary nodes pinned to ∅).

Shipped analyses, all under the Joern operator model of ``frontend.py``
(textual variable identity — ``*p`` and ``a[i]`` are variables, matching the
reference's reaching-defs semantics):

- :func:`reaching_definitions` — forward-may; facts are
  :class:`VariableDefinition`; the first client of the framework
  (``cpg/dataflow.py`` keeps the historical API on top of it);
- :func:`liveness` — backward-may; facts are variable codes; a plain
  assignment's bare-identifier lvalue is not a read, compound ops read
  their lvalue;
- :func:`uninitialized` — forward-may possibly-uninitialized locals: gen at
  METHOD entry (all of that method's LOCALs), strong kill at bare-identifier
  defs; :func:`uninitialized_uses` flags IDENTIFIER reads of in-set vars
  (``&x`` is not a read);
- :func:`solve_taint` — forward-may reachability from METHOD_PARAMETER_IN
  names and configurable source APIs (:data:`DEFAULT_TAINT_SOURCES`);
  assignment propagation is gen/kill with an outer monotone iteration
  (gens only grow, so the loop terminates).
"""

from __future__ import annotations

import ctypes
import dataclasses
import subprocess
import warnings
from pathlib import Path
from typing import Callable, Hashable, Iterable, Mapping

import numpy as np

from deepdfa_tpu.cpg.schema import CPG

__all__ = [
    "ASSIGNMENT_OPS",
    "INC_DEC_OPS",
    "MOD_OPS",
    "PLAIN_ASSIGNMENT",
    "VariableDefinition",
    "assigned_variable",
    "defined_identifier",
    "Problem",
    "Solution",
    "solve_sets",
    "solve_bitvec",
    "solve_native",
    "reaching_definitions",
    "liveness",
    "uninitialized",
    "uninitialized_uses",
    "DEFAULT_TAINT_SOURCES",
    "solve_taint",
    "taint_node_codes",
    "ANALYSES",
    "solve_analysis",
]

# ---------------------------------------------------------------- operators

ASSIGNMENT_OPS = tuple(
    "<operator>." + n
    for n in (
        "assignment",
        "assignmentAnd",
        "assignmentArithmeticShiftRight",
        "assignmentDivision",
        "assignmentExponentiation",
        "assignmentLogicalShiftRight",
        "assignmentMinus",
        "assignmentModulo",
        "assignmentMultiplication",
        "assignmentOr",
        "assignmentPlus",
        "assignmentShiftLeft",
        "assignmentXor",
    )
)
INC_DEC_OPS = tuple(
    "<operator>." + n
    for n in ("incBy", "postDecrement", "postIncrement", "preDecrement", "preIncrement")
)
# Joern emits "<operators>" for some programs; accept both spellings.
MOD_OPS = frozenset(
    ASSIGNMENT_OPS
    + INC_DEC_OPS
    + tuple(op.replace("<operator>", "<operators>") for op in ASSIGNMENT_OPS + INC_DEC_OPS)
)
# `x = e` does not read x; `x += e` / `x++` do.
PLAIN_ASSIGNMENT = frozenset({"<operator>.assignment", "<operators>.assignment"})
_ADDRESS_OF = frozenset({"<operator>.addressOf", "<operators>.addressOf"})


@dataclasses.dataclass(frozen=True)
class VariableDefinition:
    var: str
    node: int
    code: str = ""

    def __hash__(self):
        return self.node

    def __eq__(self, other):
        return self.node == other.node


def assigned_variable(cpg: CPG, nid: int) -> str | None:
    """The defined variable's source text, or None.

    First ARGUMENT child by ``order`` of a mod-op call; the child's ``code``
    is the variable expression (handles ``*p``, ``a[i]`` the way the
    reference does — textually).
    """
    node = cpg.nodes.get(nid)
    if node is None or node.name not in MOD_OPS:
        return None
    args = cpg.arguments(nid)
    if not args:
        return None
    first = args[min(args)]
    return cpg.nodes[first].code if first in cpg.nodes else None


def defined_identifier(cpg: CPG, nid: int) -> str | None:
    """The defined variable's name iff the lvalue is a bare IDENTIFIER — the
    only shape that admits a strong update (``*p``/``a[i]`` may alias)."""
    node = cpg.nodes.get(nid)
    if node is None or node.name not in MOD_OPS:
        return None
    args = cpg.arguments(nid)
    if not args:
        return None
    first = cpg.nodes.get(args[min(args)])
    if first is not None and first.label == "IDENTIFIER":
        return first.code
    return None


def _subtree(cpg: CPG, nid: int) -> list[int]:
    return [nid, *cpg.ast_descendants(nid)]


def _unread_lvalue_nodes(cpg: CPG, nid: int) -> set[int]:
    """AST nodes under ``nid`` that are written, not read: the lvalue root of
    every plain assignment in the subtree (a compound lvalue's *children*
    are still read — the address computation)."""
    out: set[int] = set()
    for c in _subtree(cpg, nid):
        node = cpg.nodes.get(c)
        if node is not None and node.name in PLAIN_ASSIGNMENT:
            args = cpg.arguments(c)
            if args:
                out.add(args[min(args)])
    return out


def _address_of_args(cpg: CPG, nid: int) -> set[int]:
    """Arguments of ``&x`` operators under ``nid`` — taking an address is
    not a read of the value."""
    out: set[int] = set()
    for c in _subtree(cpg, nid):
        node = cpg.nodes.get(c)
        if node is not None and node.name in _ADDRESS_OF:
            args = cpg.arguments(c)
            if args:
                out.add(args[min(args)])
    return out


# ---------------------------------------------------------------- framework


@dataclasses.dataclass
class Problem:
    """One monotone gen/kill dataflow instance over ``cpg``'s CFG.

    ``facts`` fixes the bit-vector layout (bit j = ``facts[j]``); ``gen`` /
    ``kill`` map CFG node id → set of facts. Transfer is
    ``out = gen ∪ (in − kill)`` on the direction-adjusted graph.
    """

    cpg: CPG
    direction: str  # "forward" | "backward"
    meet: str  # "may" | "must"
    facts: tuple[Hashable, ...]
    gen: Mapping[int, set]
    kill: Mapping[int, set]
    name: str = ""

    def __post_init__(self):
        if self.direction not in ("forward", "backward"):
            raise ValueError(f"direction must be forward|backward, got {self.direction!r}")
        if self.meet not in ("may", "must"):
            raise ValueError(f"meet must be may|must, got {self.meet!r}")
        self.nodes: list[int] = sorted(self.cpg.edge_nodes("CFG"))
        # Clip gen/kill to the declared fact universe so every backend sees
        # the same instance (a kill of a non-fact is a no-op anyway, but the
        # sets backend would otherwise happily gen one).
        universe = set(self.facts)
        self.gen = {n: set(s) & universe for n, s in self.gen.items()}
        self.kill = {n: set(s) & universe for n, s in self.kill.items()}

    def _edges(self, nid: int, incoming: bool) -> list[int]:
        """Direction-adjusted CFG neighbours: a backward problem walks the
        reversed graph, so its "predecessors" are CFG successors."""
        fwd = self.direction == "forward"
        if incoming == fwd:
            return self.cpg.predecessors(nid, "CFG")
        return self.cpg.successors(nid, "CFG")


@dataclasses.dataclass
class Solution:
    """Program-order fixpoint: ``in_facts[n]`` holds *before* node ``n``
    executes, ``out_facts[n]`` after — for backward problems too (the solver
    swaps its reversed-graph orientation back)."""

    in_facts: dict[int, set]
    out_facts: dict[int, set]


def _oriented(p: Problem, solver_in: dict, solver_out: dict) -> Solution:
    if p.direction == "forward":
        return Solution(solver_in, solver_out)
    return Solution(solver_out, solver_in)


def solve_sets(p: Problem) -> Solution:
    """Reference Python-sets chaotic-iteration worklist."""
    nodes = p.nodes
    known = set(nodes)
    must = p.meet == "must"
    full = set(p.facts)
    out_sets: dict[int, set] = {n: (set(full) if must else set()) for n in nodes}
    in_sets: dict[int, set] = {n: set() for n in nodes}
    work = list(nodes)
    while work:
        n = work.pop()
        preds = [q for q in p._edges(n, incoming=True) if q in known]
        if not preds:
            in_n: set = set()
        elif must:
            in_n = set.intersection(*(out_sets[q] for q in preds))
        else:
            in_n = set().union(*(out_sets[q] for q in preds))
        in_sets[n] = in_n
        new_out = set(p.gen.get(n, ())) | (in_n - set(p.kill.get(n, ())))
        if new_out != out_sets[n]:
            out_sets[n] = new_out
            work.extend(s for s in p._edges(n, incoming=False) if s in known)
    return _oriented(p, in_sets, out_sets)


def _encode(p: Problem):
    """Index nodes and facts; gen/kill bool matrices + direction-adjusted
    predecessor/successor index lists (shared by the vector solvers)."""
    nodes = p.nodes
    idx = {n: i for i, n in enumerate(nodes)}
    fidx = {f: j for j, f in enumerate(p.facts)}
    n, m = len(nodes), len(p.facts)
    gen = np.zeros((n, m), dtype=bool)
    kill = np.zeros((n, m), dtype=bool)
    for nid in nodes:
        i = idx[nid]
        for f in p.gen.get(nid, ()):
            gen[i, fidx[f]] = True
        for f in p.kill.get(nid, ()):
            kill[i, fidx[f]] = True
    preds = [[idx[q] for q in p._edges(nid, incoming=True) if q in idx] for nid in nodes]
    succs = [[idx[q] for q in p._edges(nid, incoming=False) if q in idx] for nid in nodes]
    return nodes, gen, kill, preds, succs


def _decode(p: Problem, nodes: list[int], mat: np.ndarray) -> dict[int, set]:
    facts = np.empty(len(p.facts), dtype=object)
    for j, f in enumerate(p.facts):
        facts[j] = f
    return {nid: set(facts[mat[i]].tolist()) for i, nid in enumerate(nodes)}


def solve_bitvec(p: Problem) -> Solution:
    """NumPy bit-matrix worklist."""
    nodes, gen, kill, preds, succs = _encode(p)
    n, m = gen.shape
    if n == 0:
        return Solution({}, {})
    must = p.meet == "must"
    out = np.ones((n, m), dtype=bool) if must else np.zeros((n, m), dtype=bool)
    inn = np.zeros((n, m), dtype=bool)
    reduce_ = np.logical_and.reduce if must else np.logical_or.reduce
    work = list(range(n))
    in_work = [True] * n
    while work:
        i = work.pop()
        in_work[i] = False
        x = reduce_(out[preds[i]], axis=0) if preds[i] else np.zeros(m, dtype=bool)
        inn[i] = x
        new_out = gen[i] | (x & ~kill[i])
        if not np.array_equal(new_out, out[i]):
            out[i] = new_out
            for s in succs[i]:
                if not in_work[s]:
                    work.append(s)
                    in_work[s] = True
    return _oriented(p, _decode(p, nodes, inn), _decode(p, nodes, out))


# ---------------------------------------------------------------- native

_LIB: ctypes.CDLL | None = None
_NATIVE_ERROR: str | None = None


def _native_lib() -> ctypes.CDLL:
    """Build (via make, a no-op when up to date) and load the C++ solver.
    Raises on toolchain-less hosts — :func:`solve_native` catches and falls
    back."""
    global _LIB
    if _LIB is not None:
        return _LIB
    root = Path(__file__).resolve().parent.parent.parent / "native"
    so = root / "libdfa_solver.so"
    if not (root / "dfa_solver.cpp").exists():
        raise RuntimeError(
            "the C++ dataflow solver needs a source checkout "
            f"(native/dfa_solver.cpp not found under {root}); installed-"
            "package users get the NumPy bit-vector fallback — identical "
            "fixpoints, cross-checked by the test suite"
        )
    # Always invoke make: it is a no-op when up to date and rebuilds after
    # source edits (a stale .so would otherwise be loaded silently).
    subprocess.run(["make", "-C", str(root), "-s"], check=True)
    lib = ctypes.CDLL(str(so))
    i32p = ctypes.POINTER(ctypes.c_int32)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.solve_dataflow.restype = ctypes.c_int
    lib.solve_dataflow.argtypes = [
        ctypes.c_int32,  # n_nodes
        ctypes.c_int32,  # n_facts
        ctypes.c_int32,  # meet_is_must
        i32p, i32p,  # pred CSR (direction-adjusted)
        i32p, i32p,  # succ CSR
        u64p, u64p,  # gen, kill [n * words]
        u64p, u64p,  # out: in / out [n * words], caller-initialised
    ]
    lib.solve_reaching_defs.restype = ctypes.c_int
    lib.solve_reaching_defs.argtypes = lib.solve_dataflow.argtypes[:2] + lib.solve_dataflow.argtypes[3:]
    _LIB = lib
    return lib


def _try_native_lib() -> ctypes.CDLL | None:
    """One warning per process when the native solver can't build/load; all
    later calls silently take the bit-vector fallback."""
    global _NATIVE_ERROR
    if _NATIVE_ERROR is not None:
        return None
    try:
        return _native_lib()
    except Exception as exc:  # noqa: BLE001 — toolchain-less hosts
        _NATIVE_ERROR = f"{type(exc).__name__}: {exc}"
        warnings.warn(
            f"native dataflow solver unavailable ({_NATIVE_ERROR}); "
            "falling back to the NumPy bit-vector solver (identical "
            "fixpoints, slower on large functions)",
            RuntimeWarning,
            stacklevel=3,
        )
        return None


def _pack_bits(mat: np.ndarray) -> np.ndarray:
    """bool [n, m] → uint64 [n, ceil(m/64)] little-endian bit packing."""
    n, m = mat.shape
    words = max((m + 63) // 64, 1)
    padded = np.zeros((n, words * 64), dtype=bool)
    padded[:, :m] = mat
    b = np.packbits(padded, axis=1, bitorder="little")
    return b.reshape(n, words, 8).view(np.uint64).reshape(n, words)


def _unpack_bits(packed: np.ndarray, m: int) -> np.ndarray:
    n, words = packed.shape
    bytes_ = packed.reshape(n, words, 1).view(np.uint8).reshape(n, words * 8)
    bits = np.unpackbits(bytes_, axis=1, bitorder="little")
    return bits[:, :m].astype(bool)


def _csr(lists: list[list[int]]) -> tuple[np.ndarray, np.ndarray]:
    indptr = np.zeros(len(lists) + 1, dtype=np.int32)
    for i, l in enumerate(lists):
        indptr[i + 1] = indptr[i] + len(l)
    indices = np.concatenate([np.array(l, dtype=np.int32) for l in lists]) if any(lists) else np.zeros(0, np.int32)
    return indptr, indices


def solve_native(p: Problem) -> Solution:
    """C++ CSR worklist; output contract identical to :func:`solve_bitvec`.
    Falls back to the bit-vector solver when no C++ toolchain is available
    (one warning per process)."""
    lib = _try_native_lib()
    if lib is None:
        return solve_bitvec(p)
    nodes, gen, kill, preds, succs = _encode(p)
    n, m = gen.shape
    if n == 0:
        return Solution({}, {})
    words = max((m + 63) // 64, 1)
    must = p.meet == "must"
    gen_p = np.ascontiguousarray(_pack_bits(gen))
    kill_p = np.ascontiguousarray(_pack_bits(kill))
    in_p = np.zeros((n, words), dtype=np.uint64)
    # must starts at TOP (all facts, padding bits included — they are
    # sliced off at unpack), may at ⊥
    fill = np.uint64(0xFFFFFFFFFFFFFFFF) if must else np.uint64(0)
    out_p = np.full((n, words), fill, dtype=np.uint64)
    pp, pi = _csr(preds)
    sp, si = _csr(succs)

    as_u64 = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))
    as_i32 = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
    rc = lib.solve_dataflow(
        n, m, int(must), as_i32(pp), as_i32(pi), as_i32(sp), as_i32(si),
        as_u64(gen_p), as_u64(kill_p), as_u64(in_p), as_u64(out_p),
    )
    if rc != 0:
        raise RuntimeError(f"native solver failed with rc={rc}")
    inn = _unpack_bits(in_p, m)
    out = _unpack_bits(out_p, m)
    return _oriented(p, _decode(p, nodes, inn), _decode(p, nodes, out))


# ---------------------------------------------------------------- analyses


def _sorted_codes(codes: Iterable[str]) -> tuple[str, ...]:
    return tuple(sorted(set(codes)))


def reaching_definitions(cpg: CPG) -> Problem:
    """Forward-may reaching definitions — the framework formulation of the
    historical :class:`deepdfa_tpu.cpg.dataflow.ReachingDefinitions` (which
    now delegates here; identical semantics incl. the textual-variable and
    ``<operators>`` quirks)."""
    gen: dict[int, set] = {}
    by_var: dict[str, set[VariableDefinition]] = {}
    for nid in cpg.nodes:
        var = assigned_variable(cpg, nid)
        if var is None:
            gen[nid] = set()
            continue
        d = VariableDefinition(var, nid, cpg.nodes[nid].code)
        gen[nid] = {d}
        by_var.setdefault(var, set()).add(d)
    kill = {
        nid: {d for d in by_var.get(assigned_variable(cpg, nid) or "", ()) if d.node != nid}
        for nid in cpg.nodes
    }
    facts = tuple(sorted(set().union(*by_var.values()) if by_var else set(), key=lambda d: d.node))
    return Problem(cpg, "forward", "may", facts, gen, kill, name="reaching_defs")


def liveness(cpg: CPG) -> Problem:
    """Backward-may live variables over textual variable codes.

    ``use(n)``: IDENTIFIER codes in the statement's subtree (minus plain-
    assignment lvalues) plus compound lvalue codes (``*p``, ``a[i]``) that
    are defined somewhere in the function; ``def(n)``: the assigned code.
    Program-order ``out_facts`` is ``live_out`` — the feature family
    ``_DFA_live_out`` counts it.
    """
    def_codes = {assigned_variable(cpg, n) for n in cpg.nodes} - {None}
    cfg_nodes = cpg.edge_nodes("CFG")
    gen: dict[int, set] = {}
    kill: dict[int, set] = {}
    all_uses: set[str] = set()
    for n in cfg_nodes:
        unread = _unread_lvalue_nodes(cpg, n)
        uses: set[str] = set()
        for d in _subtree(cpg, n):
            if d in unread:
                continue
            nd = cpg.nodes.get(d)
            if nd is None:
                continue
            if nd.label == "IDENTIFIER" or (nd.label == "CALL" and nd.code in def_codes):
                uses.add(nd.code)
        var = assigned_variable(cpg, n)
        gen[n] = uses
        kill[n] = {var} if var is not None else set()
        all_uses |= uses
    facts = _sorted_codes(all_uses | def_codes)
    return Problem(cpg, "backward", "may", facts, gen, kill, name="liveness")


def _method_ast_map(cpg: CPG, label: str) -> dict[int, set[str]]:
    """METHOD node id → names of its ``label``-labelled AST descendants
    (per-method scoping for merged multi-function CPGs)."""
    out: dict[int, set[str]] = {}
    for n in cpg.nodes.values():
        if n.label != "METHOD":
            continue
        out[n.id] = {
            cpg.nodes[d].name
            for d in cpg.ast_descendants(n.id)
            if d in cpg.nodes and cpg.nodes[d].label == label and cpg.nodes[d].name
        }
    return out


def uninitialized(cpg: CPG) -> Problem:
    """Forward-may possibly-uninitialized locals: every LOCAL of a method is
    generated at its METHOD entry and killed by a bare-identifier definition.
    A node reads a possibly-uninit var iff it uses a name still in its IN set
    (:func:`uninitialized_uses`)."""
    locals_by_method = _method_ast_map(cpg, "LOCAL")
    cfg_nodes = cpg.edge_nodes("CFG")
    gen = {n: set() for n in cfg_nodes}
    for mid, names in locals_by_method.items():
        if mid in gen:
            gen[mid] = set(names)
    kill: dict[int, set] = {}
    for n in cfg_nodes:
        name = defined_identifier(cpg, n)
        kill[n] = {name} if name is not None else set()
    facts = _sorted_codes(set().union(*locals_by_method.values()) if locals_by_method else set())
    return Problem(cpg, "forward", "may", facts, gen, kill, name="uninit")


def uninitialized_uses(cpg: CPG, solution: Solution) -> dict[int, set[str]]:
    """Node id → local names read while possibly uninitialized. Reads are
    bare IDENTIFIERs only; plain-assignment lvalues and ``&x`` arguments are
    writes/address-takes, not reads."""
    flags: dict[int, set[str]] = {}
    for n, in_facts in solution.in_facts.items():
        if not in_facts:
            continue
        skip = _unread_lvalue_nodes(cpg, n) | _address_of_args(cpg, n)
        reads = {
            cpg.nodes[d].code
            for d in _subtree(cpg, n)
            if d not in skip and d in cpg.nodes and cpg.nodes[d].label == "IDENTIFIER"
        }
        bad = reads & in_facts
        if bad:
            flags[n] = bad
    return flags


DEFAULT_TAINT_SOURCES = frozenset({
    "fgetc", "fgets", "fread", "fscanf", "getc", "getchar", "getenv",
    "gets", "read", "recv", "recvfrom", "scanf",
})


def _taint_static(cpg: CPG, source_apis: frozenset[str]):
    """Static part of the taint instance: seed gens (params at METHOD entry,
    source-API results, identifier and address-of arguments of source calls
    — ``gets(buf)`` writes through buf), strong kills at defs, per-def RHS
    mention sets for the propagation rounds."""
    params_by_method = _method_ast_map(cpg, "METHOD_PARAMETER_IN")
    cfg_nodes = cpg.edge_nodes("CFG")
    base_gen: dict[int, set] = {n: set() for n in cfg_nodes}
    kill: dict[int, set] = {n: set() for n in cfg_nodes}
    def_var: dict[int, str] = {}
    def_rhs: dict[int, set[str]] = {}
    facts: set[str] = set().union(*params_by_method.values()) if params_by_method else set()

    for mid, names in params_by_method.items():
        if mid in base_gen:
            base_gen[mid] = set(names)

    for n in cfg_nodes:
        var = assigned_variable(cpg, n)
        sub = _subtree(cpg, n)
        # the METHOD entry's AST subtree is the whole function — scanning it
        # for source calls would taint their args from entry; a call's taint
        # belongs to the statement node that contains it
        source_calls = [] if cpg.nodes[n].label == "METHOD" else [
            c for c in sub
            if c in cpg.nodes
            and cpg.nodes[c].label == "CALL"
            and cpg.nodes[c].name in source_apis
        ]
        if var is not None:
            facts.add(var)
            kill[n] = {var}
            def_var[n] = var
            # RHS mentions (textual, compound codes included) drive the
            # propagation rounds; the plain-assignment lvalue subtree is
            # written, not read, so `x = 0` untaints x
            excl: set[int] = set()
            node = cpg.nodes.get(n)
            if node is not None and node.name in PLAIN_ASSIGNMENT:
                args = cpg.arguments(n)
                if args:
                    lv = args[min(args)]
                    excl = {lv, *cpg.ast_descendants(lv)}
            def_rhs[n] = {
                cpg.nodes[d].code
                for d in sub
                if d not in excl and d != n and d in cpg.nodes
                and cpg.nodes[d].label in ("IDENTIFIER", "CALL")
            }
            if source_calls:
                base_gen[n].add(var)
        for c in source_calls:
            # out-buffers are passed bare (array decay: gets(buf)) or by
            # address (scanf("%d", &x)); both taint the argument.  Bare
            # identifier args over-taint counts/fds — conservative for may.
            tainted_args = {
                a for a in cpg.arguments(c).values()
                if a in cpg.nodes and cpg.nodes[a].label == "IDENTIFIER"
            }
            tainted_args |= _address_of_args(cpg, c)
            for a in tainted_args:
                nd = cpg.nodes.get(a)
                if nd is not None and nd.code:
                    facts.add(nd.code)
                    base_gen[n].add(nd.code)
    return _sorted_codes(facts), base_gen, kill, def_var, def_rhs


def solve_taint(
    cpg: CPG,
    source_apis: frozenset[str] = DEFAULT_TAINT_SOURCES,
    solver: Callable[[Problem], Solution] = solve_bitvec,
) -> Solution:
    """Parameter/API taint reachability fixpoint.

    Assignment propagation ("``x = f(y)`` taints x when y is tainted") makes
    gen depend on the solution, so the inner gen/kill solve sits in an outer
    iteration that re-derives the conditional gens from the last fixpoint.
    Gens only ever grow (in-sets grow monotonically with gens), so the loop
    terminates in ≤ |facts| rounds — and every backend reaches the same
    fixpoint because each round's Problem is identical across backends.
    """
    facts, base_gen, kill, def_var, def_rhs = _taint_static(cpg, source_apis)
    extra: dict[int, set] = {n: set() for n in base_gen}
    while True:
        gen = {n: base_gen[n] | extra[n] for n in base_gen}
        sol = solver(Problem(cpg, "forward", "may", facts, gen, kill, name="taint"))
        changed = False
        for n, var in def_var.items():
            if var in gen[n]:
                continue
            if def_rhs[n] & sol.in_facts.get(n, set()):
                extra[n].add(var)
                changed = True
        if not changed:
            return sol


def taint_node_codes(
    cpg: CPG,
    source_apis: frozenset[str] = DEFAULT_TAINT_SOURCES,
    solver: Callable[[Problem], Solution] = solve_bitvec,
) -> dict[int, int]:
    """Per-CFG-node taint code for the ``_DFA_taint`` feature family:
    0 = untouched, 1 = uses a tainted variable, 2 = introduces/propagates
    taint (source call, tainted assignment, or parameter entry)."""
    facts, base_gen, kill, def_var, def_rhs = _taint_static(cpg, source_apis)
    sol = solve_taint(cpg, source_apis, solver)
    out: dict[int, int] = {}
    for n, in_facts in sol.in_facts.items():
        # a node introduces taint iff its OUT has facts survival can't explain
        gens = sol.out_facts.get(n, set()) - (in_facts - kill.get(n, set()))
        if gens:
            out[n] = 2
            continue
        mentions = {
            cpg.nodes[d].code
            for d in _subtree(cpg, n)
            if d in cpg.nodes and cpg.nodes[d].label in ("IDENTIFIER", "CALL")
        }
        out[n] = 1 if mentions & in_facts else 0
    return out


# ------------------------------------------------------------- registry

ANALYSES = ("reaching_defs", "liveness", "uninit", "taint")

_BACKENDS: dict[str, Callable[[Problem], Solution]] = {
    "sets": solve_sets,
    "bitvec": solve_bitvec,
    "native": solve_native,
}


def solve_analysis(name: str, cpg: CPG, backend: str = "bitvec") -> Solution:
    """Solve one named analysis with one backend — the uniform entry point
    used by the parity tests and the throughput bench."""
    solver = _BACKENDS[backend]
    if name == "taint":
        return solve_taint(cpg, solver=solver)
    builders = {
        "reaching_defs": reaching_definitions,
        "liveness": liveness,
        "uninit": uninitialized,
    }
    return solver(builders[name](cpg))
