"""Joern artifact ingestion + offline runner.

Readers for the three per-function artifacts the reference's Joern script
exports (``DDFA/storage/external/get_func_graph.sc:49-75``):

- ``{f}.nodes.json`` — list of node property dicts;
- ``{f}.edges.json`` — list of ``[innode, outnode, etype, variable]`` rows
  (Joern edge: outNode → inNode, so src=outnode);
- ``{f}.dataflow.json`` — per-method ``problem.gen/problem.kill/
  solution.in/solution.out`` maps (node id → list of def node ids).

:func:`load_cpg` follows the reference's analysis-side cleanup contract
(``code_gnn/analysis/dataflow.py:201-250``): keep nodes with line numbers,
drop dangling/lone nodes, dedupe edges. :func:`load_tables` mirrors the
ML-side cleanup (``helpers/joern.py:182-319``) used for graph
materialisation: label/edge-type filtering and TYPE-node synthesis.

:class:`JoernRunner` shells out to a local joern install (the reference
pinned v1.1.107); it is optional — the native frontend
(:mod:`deepdfa_tpu.cpg.frontend`) is the hermetic default.
"""

from __future__ import annotations

import json
import shutil
import subprocess
from pathlib import Path

import pandas as pd

from deepdfa_tpu.cpg.schema import CPG
from deepdfa_tpu.resilience.journal import atomic_write_text

__all__ = [
    "load_tables", "load_cpg", "load_dataflow", "reexport_dataflow",
    "JoernRunner",
]

NODE_COLUMNS = [
    "id", "_label", "name", "code", "lineNumber", "columnNumber",
    "lineNumberEnd", "columnNumberEnd", "controlStructureType", "order",
    "fullName", "typeFullName",
]

# Edge types that are bookkeeping, not program structure.
DROP_ETYPES = {"CONTAINS", "SOURCE_FILE", "DOMINATE", "POST_DOMINATE"}
DROP_LABELS = {"COMMENT", "FILE"}


def read_raw(stem: str | Path) -> tuple[pd.DataFrame, pd.DataFrame]:
    """Read ``{stem}.nodes.json`` / ``{stem}.edges.json`` into raw tables."""
    stem = str(stem)
    with open(stem + ".edges.json") as f:
        edges = pd.DataFrame(
            json.load(f), columns=["innode", "outnode", "etype", "dataflow"]
        ).fillna("")
    with open(stem + ".nodes.json") as f:
        nodes = pd.DataFrame.from_records(json.load(f), columns=NODE_COLUMNS).fillna("")
    return nodes, edges


def load_tables(stem: str | Path) -> tuple[pd.DataFrame, pd.DataFrame]:
    """ML-side tables: filtered labels/etypes, int lines, deduped edges."""
    nodes, edges = read_raw(stem)
    if (nodes._label == "METHOD").sum() == 0:
        raise ValueError(f"{stem}: graph has no METHOD node")
    nodes = nodes[~nodes._label.isin(DROP_LABELS)].copy()
    edges = edges[~edges.etype.isin(DROP_ETYPES)].copy()
    nodes.code = nodes.code.replace("<empty>", "")
    nodes.code = nodes.apply(lambda r: r.code if r.code != "" else r["name"], axis=1)
    nodes.lineNumber = pd.to_numeric(nodes.lineNumber, errors="coerce")
    edges.innode = pd.to_numeric(edges.innode, errors="coerce")
    edges.outnode = pd.to_numeric(edges.outnode, errors="coerce")
    edges = edges.dropna(subset=["innode", "outnode"])
    edges = edges.astype({"innode": int, "outnode": int})
    edges = edges.drop_duplicates(subset=["innode", "outnode", "etype"])
    return nodes, edges


def load_cpg(stem: str | Path) -> CPG:
    """Analysis-side CPG (reaching definitions, abstract dataflow): nodes with
    line numbers, dangling edges dropped, no lone nodes."""
    nodes, edges = load_tables(stem)
    nodes = nodes[nodes.lineNumber.notna()].copy()
    nodes.lineNumber = nodes.lineNumber.astype(int)
    ids = set(nodes.id.astype(int))
    edges = edges[edges.innode.isin(ids) & edges.outnode.isin(ids)]
    connected = set(edges.innode) | set(edges.outnode)
    nodes = nodes[nodes.id.isin(connected)]
    return CPG.from_tables(nodes, edges)


def load_dataflow(path: str | Path) -> dict:
    """Parse ``{f}.dataflow.json`` → {method: {key: {node_id: [def ids]}}}
    with int keys (reference loader: ``helpers/datasets.py:780-796``)."""
    with open(str(path)) as f:
        raw = json.load(f)
    out: dict = {}
    for method, solution in raw.items():
        out[method] = {
            key: {int(k): [int(v) for v in vs] for k, vs in mapping.items()}
            for key, mapping in solution.items()
        }
    return out


def reexport_dataflow(stem: str | Path, cache: bool = True) -> Path:
    """Summary-cached dataflow RE-export, native solver edition (capability
    parity with ``DDFA/storage/external/get_dataflow_output.sc:26-75``):
    re-run reaching definitions over the CACHED extraction artifacts
    (``{stem}.nodes.json``/``.edges.json`` — no re-extraction, no JVM) and
    (re)write ``{stem}.dataflow.json`` in the reference schema.

    Cache contract mirrors the reference script: if
    ``{stem}.dataflow.summary.json`` exists and ``cache=True`` the call is a
    no-op. On a successful re-solve the summary marker is written too (the
    reference checks the marker but never writes it — a permanently cold
    cache; writing it is the evident intent). ``cache=False`` forces the
    re-solve. The Joern-path twin is
    ``deepdfa_tpu/cpg/queries/reexport_dataflow.sc``.
    """
    from deepdfa_tpu.cpg.dataflow import ReachingDefinitions

    stem = str(stem)
    out_path = Path(stem + ".dataflow.json")
    summary_path = Path(stem + ".dataflow.summary.json")
    if cache and summary_path.exists():
        return out_path

    cpg = load_cpg(stem)
    rd = ReachingDefinitions(cpg)
    in_sets, out_sets = rd.solve()
    methods = [
        n for n in cpg.nodes.values()
        if n.label == "METHOD" and n.name not in ("<global>", "<empty>", "")
    ]

    def ast_descendants(root: int) -> set[int]:
        seen, work = {root}, [root]
        while work:
            for c in cpg.successors(work.pop(), "AST"):
                if c not in seen:
                    seen.add(c)
                    work.append(c)
        return seen

    # per-method sets, like the Joern twin's per-method ReachingDefProblem:
    # restrict keys to the method's AST subtree (a multi-method artifact
    # must not attribute one function's definitions to another)
    member: dict[str, set[int]] | None = None
    if len(methods) > 1:
        member = {m.name: ast_descendants(m.id) for m in methods}

    def node_sets(
        sets_by_node: dict[int, set], keep: set[int] | None
    ) -> dict[str, list[int]]:
        return {
            str(n): sorted(d.node for d in s)
            for n, s in sorted(sets_by_node.items())
            if keep is None or n in keep
        }

    gen = {n: s for n, s in rd.gen.items() if s}
    kill = {n: rd.kill(n, rd.domain) for n in gen}
    per_method = {}
    for m in methods or [None]:
        name = m.name if m is not None else Path(stem).stem
        keep = member.get(name) if (member and m is not None) else None
        per_method[name] = {
            "problem.gen": node_sets(gen, keep),
            "problem.kill": node_sets(kill, keep),
            "solution.in": node_sets(in_sets, keep),
            "solution.out": node_sets(out_sets, keep),
        }
    atomic_write_text(out_path, json.dumps(per_method))
    atomic_write_text(summary_path, json.dumps({
        "methods": len(per_method),
        "solved_nodes": {k: len(v["solution.in"]) for k, v in per_method.items()},
        "domain_size": len(rd.domain),
        "solver": "native",
    }))
    return out_path


class JoernRunner:
    """Batch runner for a local joern install (optional path).

    One-shot invocation per file, parity with ``helpers/joern.py:162-179``:
    ``joern --script get_func_graph.sc --params filename=...``. Exports land
    next to the source file; re-runs are skipped when artifacts exist (the
    reference's idempotence contract, ``get_func_graph.sc:36-48``).
    """

    def __init__(self, script: str | Path | None = None, joern_bin: str = "joern"):
        if script is None:  # the framework ships its own query script
            script = Path(__file__).parent / "queries" / "export_func_graph.sc"
        self.script = Path(script)
        self.joern_bin = joern_bin

    @property
    def available(self) -> bool:
        return shutil.which(self.joern_bin) is not None

    def run(self, c_file: str | Path, timeout: int = 600) -> Path:
        c_file = Path(c_file)
        stem = str(c_file)
        if Path(stem + ".nodes.json").exists() and Path(stem + ".edges.json").exists():
            return c_file
        if not self.available:
            raise RuntimeError(
                f"joern binary {self.joern_bin!r} not on PATH; use the native "
                "frontend (deepdfa_tpu.cpg.frontend) or install joern"
            )
        subprocess.run(
            [self.joern_bin, "--script", str(self.script), "--params", f"filename={stem}"],
            check=True,
            timeout=timeout,
            capture_output=True,
        )
        return c_file

    def reexport_dataflow(self, c_file: str | Path, cache: bool = True,
                          timeout: int = 600) -> Path:
        """JVM-path summary-cached re-solve over the cached ``.cpg.bin``
        (``queries/reexport_dataflow.sc``; reference:
        ``get_dataflow_output.sc:26-75``). Prefer the module-level
        :func:`reexport_dataflow` (native solver, no JVM) unless Joern's own
        solver output is specifically required."""
        if not self.available:
            raise RuntimeError(
                f"joern binary {self.joern_bin!r} not on PATH; use the native "
                "reexport_dataflow (deepdfa_tpu.cpg.joern) instead"
            )
        stem = str(Path(c_file))
        script = Path(__file__).parent / "queries" / "reexport_dataflow.sc"
        params = f"filename={stem},cache={'true' if cache else 'false'}"
        subprocess.run(
            [self.joern_bin, "--script", str(script), "--params", params],
            check=True, timeout=timeout, capture_output=True,
        )
        return Path(stem + ".dataflow.json")
