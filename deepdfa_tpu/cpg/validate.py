"""CPG structural validator.

The extraction/feature pipeline silently assumes a handful of invariants
about the graphs the frontend (or a Joern export) hands it. A malformed
graph does not crash downstream — it quietly corrupts features (a dangling
CFG edge truncates fixpoints, a duplicate ARGUMENT order makes
``assigned_variable`` nondeterministic). :func:`validate_cpg` checks the
invariants explicitly and returns structured :class:`Diagnostic` records:

- ``dangling-edge`` (error) — an edge endpoint that is not a node;
- ``method-root`` (error) — a CFG weakly-connected component with zero or
  multiple METHOD nodes (multi-function CPGs from ``parse_source`` are one
  component per function, so the check is per-component, not global);
- ``unreachable-return`` (error) — a METHOD_RETURN not reachable from its
  METHOD along CFG edges (the fixpoint never sees the exit state);
- ``argument-order-duplicate`` (error) — two ARGUMENT children of one call
  with the same ``order`` (``CPG.arguments`` would silently drop one);
- ``argument-order-sparse`` (warning) — ARGUMENT orders not dense 1..k;
- ``unknown-operator`` (error) — a ``<operator>.X`` call name outside the
  vocabulary the frontend/Joern operator model can emit (definitely a
  corrupt or foreign graph; the dataflow suite would treat it as an
  opaque call);
- ``no-method`` (error) — a CPG with no METHOD node at all.

The call-graph contract (the interprocedural layer,
:mod:`deepdfa_tpu.cpg.interproc`): supergraph construction is total — a
malformed callee reference degrades to a summarized external, never a
KeyError — and THESE checks are where the degradation surfaces as
quarantine-compatible rows:

- ``call-ref-malformed`` (error) — a CALL carrying ARGUMENT children but
  an empty callee name: neither resolvable to a METHOD nor summarizable
  by name;
- ``call-ref-ambiguous`` (warning) — two METHODs share one name, so call
  resolution (lowest METHOD id) is arbitrary;
- ``call-arity`` (warning) — a resolved call whose ARGUMENT count differs
  from the callee's METHOD_PARAMETER_IN count (the supergraph binds the
  common prefix and leaves the rest unconstrained);
- ``call-no-return`` (warning) — a resolved callee METHOD without a
  METHOD_RETURN child (the supergraph links parameters but cannot route
  the return value).

``severity`` is ``"error"`` for invariants whose violation corrupts
features (ingestion drops the graph) and ``"warning"`` for oddities worth
surfacing but survivable. :func:`validate_corpus` aggregates per-dataset
counts for the ingestion summary.
"""

from __future__ import annotations

import dataclasses
from collections import defaultdict
from typing import Iterable, Mapping

from deepdfa_tpu.cpg.analyses import ASSIGNMENT_OPS, INC_DEC_OPS
from deepdfa_tpu.cpg.schema import CPG

__all__ = ["Diagnostic", "KNOWN_OPERATOR_NAMES", "validate_cpg", "validate_corpus"]


def _known_operators() -> frozenset[str]:
    from deepdfa_tpu.cpg.frontend import ASSIGN_OPS, BINARY_OPS, UNARY_OPS

    names = set(BINARY_OPS.values()) | set(ASSIGN_OPS.values()) | set(UNARY_OPS.values())
    names |= {
        "indexAccess", "indirectIndexAccess", "fieldAccess",
        "indirectFieldAccess", "cast", "conditional", "sizeOf",
    }
    # Joern-only spellings the frontend never emits but real exports contain
    names |= {op.split(".", 1)[1] for op in ASSIGNMENT_OPS + INC_DEC_OPS}
    return frozenset(f"{pre}.{n}" for pre in ("<operator>", "<operators>") for n in names)


KNOWN_OPERATOR_NAMES = _known_operators()


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    check: str
    severity: str  # "error" | "warning"
    message: str
    node: int | None = None
    edge: tuple[int, int, str] | None = None

    def __str__(self):
        where = f" node={self.node}" if self.node is not None else ""
        where += f" edge={self.edge}" if self.edge is not None else ""
        return f"[{self.severity}] {self.check}:{where} {self.message}"


def _cfg_components(cpg: CPG) -> list[set[int]]:
    """Weakly-connected components of the CFG subgraph."""
    adj: dict[int, set[int]] = defaultdict(set)
    nodes: set[int] = set()
    for s, d, e in cpg.edges:
        if e != "CFG" or s not in cpg.nodes or d not in cpg.nodes:
            continue
        adj[s].add(d)
        adj[d].add(s)
        nodes |= {s, d}
    seen: set[int] = set()
    comps: list[set[int]] = []
    for n in nodes:
        if n in seen:
            continue
        comp: set[int] = set()
        stack = [n]
        while stack:
            x = stack.pop()
            if x in comp:
                continue
            comp.add(x)
            stack.extend(adj[x] - comp)
        seen |= comp
        comps.append(comp)
    return comps


def _cfg_reachable(cpg: CPG, start: int) -> set[int]:
    seen: set[int] = set()
    stack = [start]
    while stack:
        n = stack.pop()
        if n in seen:
            continue
        seen.add(n)
        stack.extend(s for s in cpg.successors(n, "CFG") if s not in seen)
    return seen


def validate_cpg(cpg: CPG) -> list[Diagnostic]:
    """All structural diagnostics for one CPG, errors first."""
    diags: list[Diagnostic] = []

    # -- dangling edge endpoints (any edge type)
    for s, d, e in cpg.edges:
        missing = [x for x in (s, d) if x not in cpg.nodes]
        if missing:
            diags.append(Diagnostic(
                "dangling-edge", "error",
                f"{e} edge references missing node(s) {missing}",
                edge=(s, d, e),
            ))

    methods = [n for n in cpg.nodes.values() if n.label == "METHOD"]
    if not methods:
        diags.append(Diagnostic("no-method", "error", "CPG has no METHOD node"))

    # -- exactly one METHOD root per CFG component (parse_source merges
    #    functions as disjoint components, so the check is local)
    for comp in _cfg_components(cpg):
        roots = [n for n in comp if cpg.nodes[n].label == "METHOD"]
        if len(roots) != 1:
            sample = sorted(comp)[:3]
            diags.append(Diagnostic(
                "method-root", "error",
                f"CFG component containing nodes {sample} has "
                f"{len(roots)} METHOD roots (want exactly 1)",
                node=roots[0] if roots else None,
            ))

    # -- every METHOD_RETURN reachable from its method's entry via CFG
    for m in methods:
        returns = [
            d for d in cpg.ast_descendants(m.id)
            if d in cpg.nodes and cpg.nodes[d].label == "METHOD_RETURN"
        ]
        reach = _cfg_reachable(cpg, m.id)
        for r in returns:
            if r not in reach:
                diags.append(Diagnostic(
                    "unreachable-return", "error",
                    f"METHOD_RETURN {r} of method {m.name!r} is not CFG-"
                    f"reachable from METHOD {m.id}",
                    node=r,
                ))

    # -- ARGUMENT orders: duplicates are data loss, sparseness is suspect
    arg_children: dict[int, list[int]] = defaultdict(list)
    for s, d, e in cpg.edges:
        if e == "ARGUMENT" and s in cpg.nodes and d in cpg.nodes:
            arg_children[s].append(d)
    for call, children in arg_children.items():
        orders = sorted(cpg.nodes[c].order for c in children)
        if len(set(orders)) != len(orders):
            dup = next(o for o in orders if orders.count(o) > 1)
            diags.append(Diagnostic(
                "argument-order-duplicate", "error",
                f"call {call} ({cpg.nodes[call].code!r}) has multiple "
                f"ARGUMENT children with order={dup}",
                node=call,
            ))
        elif orders != list(range(1, len(orders) + 1)):
            diags.append(Diagnostic(
                "argument-order-sparse", "warning",
                f"call {call} ({cpg.nodes[call].code!r}) has non-dense "
                f"ARGUMENT orders {orders} (want 1..{len(orders)})",
                node=call,
            ))

    # -- operator-call names must be in the known vocabulary
    for n in cpg.nodes.values():
        if n.label == "CALL" and n.name.startswith("<operator") \
                and n.name not in KNOWN_OPERATOR_NAMES:
            diags.append(Diagnostic(
                "unknown-operator", "error",
                f"call {n.id} has unknown operator name {n.name!r}",
                node=n.id,
            ))

    diags.extend(_call_ref_diagnostics(cpg))

    diags.sort(key=lambda d: (d.severity != "error", d.check))
    return diags


def _call_ref_diagnostics(cpg: CPG) -> list[Diagnostic]:
    """The call-graph contract: every shape supergraph construction
    degrades on becomes a diagnostic row here (same resolution rules as
    ``cpg.callgraph.build_callgraph`` — by METHOD name, lowest id wins)."""
    from deepdfa_tpu.cpg.callgraph import build_callgraph

    diags: list[Diagnostic] = []
    cg = build_callgraph(cpg)
    for name in cg.ambiguous:
        diags.append(Diagnostic(
            "call-ref-ambiguous", "warning",
            f"method name {name!r} is defined by multiple METHOD nodes — "
            "call resolution picks the lowest id; rename or split the CPG",
            node=cg.methods.get(name),
        ))
    warned_no_return: set[int] = set()
    for site in cg.sites:
        call = cpg.nodes.get(site.call)
        if call is None:
            continue
        if not site.name and cpg.arguments(site.call):
            diags.append(Diagnostic(
                "call-ref-malformed", "error",
                f"call {site.call} has ARGUMENT children but an empty "
                "callee name — not resolvable, not summarizable",
                node=site.call,
            ))
            continue
        if site.callee is None:
            continue  # summarized external: by design, not a diagnostic
        callee = cpg.nodes.get(site.callee)
        n_params = sum(
            1 for d in cpg.successors(site.callee, "AST")
            if d in cpg.nodes and cpg.nodes[d].label == "METHOD_PARAMETER_IN"
        )
        n_args = len(cpg.arguments(site.call))
        if n_args != n_params:
            diags.append(Diagnostic(
                "call-arity", "warning",
                f"call {site.call} passes {n_args} argument(s) but method "
                f"{callee.name!r} declares {n_params} parameter(s) — the "
                "supergraph binds only the common prefix",
                node=site.call,
            ))
        has_return = any(
            d in cpg.nodes and cpg.nodes[d].label == "METHOD_RETURN"
            for d in cpg.successors(site.callee, "AST")
        )
        if not has_return and site.callee not in warned_no_return:
            warned_no_return.add(site.callee)
            diags.append(Diagnostic(
                "call-no-return", "warning",
                f"resolved callee METHOD {site.callee} ({callee.name!r}) "
                "has no METHOD_RETURN child — the supergraph cannot route "
                "its return value",
                node=site.callee,
            ))
    return diags


def validate_corpus(cpgs: Iterable[tuple[object, CPG]]) -> Mapping[str, object]:
    """Validate many graphs; returns the per-dataset summary ingestion
    reports: totals, per-check counts, and the ids of graphs with errors
    (the ones ingestion should drop)."""
    by_check: dict[str, int] = defaultdict(int)
    bad_ids: list[object] = []
    n_graphs = n_warn = 0
    for gid, cpg in cpgs:
        n_graphs += 1
        diags = validate_cpg(cpg)
        has_error = False
        for d in diags:
            by_check[d.check] += 1
            if d.severity == "error":
                has_error = True
            else:
                n_warn += 1
        if has_error:
            bad_ids.append(gid)
    return {
        "graphs": n_graphs,
        "graphs_with_errors": len(bad_ids),
        "warnings": n_warn,
        "by_check": dict(sorted(by_check.items())),
        "error_graph_ids": bad_ids,
    }
