"""Columnar code-property-graph container.

Replaces the reference's pandas+networkx ``MultiDiGraph`` CPG representation
(``code_gnn/analysis/dataflow.py:201-250``): one node table + one edge table,
with lazily built per-etype adjacency for the traversals the analyses need.
Node/edge vocabulary follows Joern's schema (labels like ``CALL``,
``IDENTIFIER``, ``LOCAL``; edge types ``AST``, ``CFG``, ``ARGUMENT``,
``REACHING_DEF``, ...) so Joern-extracted and natively-extracted graphs are
interchangeable downstream.

Edge direction convention: ``src → dst`` where ``src`` is Joern's
``outNode`` and ``dst`` its ``inNode`` (the reference builds its nx graph the
same way, ``dataflow.py:243-245``).
"""

from __future__ import annotations

import dataclasses
from collections import defaultdict
from typing import Any, Iterable

import numpy as np

__all__ = ["Node", "CPG"]

# Node labels (subset of Joern's schema that the analyses touch).
CALL = "CALL"
IDENTIFIER = "IDENTIFIER"
LITERAL = "LITERAL"
LOCAL = "LOCAL"
METHOD = "METHOD"
METHOD_RETURN = "METHOD_RETURN"
METHOD_PARAMETER_IN = "METHOD_PARAMETER_IN"
BLOCK = "BLOCK"
CONTROL_STRUCTURE = "CONTROL_STRUCTURE"
RETURN = "RETURN"


@dataclasses.dataclass
class Node:
    id: int
    label: str  # Joern ``_label``
    name: str = ""
    code: str = ""
    line: int | None = None
    order: int = 0
    type_full_name: str = ""


class CPG:
    """In-memory CPG with per-etype adjacency.

    ``nodes``: dict id → :class:`Node`. ``edges``: list of (src, dst, etype).
    """

    def __init__(self, nodes: Iterable[Node], edges: Iterable[tuple[int, int, str]]):
        self.nodes: dict[int, Node] = {n.id: n for n in nodes}
        self.edges: list[tuple[int, int, str]] = [
            (int(s), int(d), e) for s, d, e in edges
        ]
        self._succ: dict[str, dict[int, list[int]]] = {}
        self._pred: dict[str, dict[int, list[int]]] = {}

    # -- construction -----------------------------------------------------
    @classmethod
    def from_tables(cls, nodes_df, edges_df) -> "CPG":
        """Build from pandas tables with reference-compatible columns
        (``id,_label,name,code,lineNumber,order,typeFullName`` /
        ``outnode,innode,etype``)."""
        def _int_or_none(v):
            try:
                return int(v)
            except (TypeError, ValueError):
                return None

        nodes = [
            Node(
                id=int(r["id"]),
                label=str(r.get("_label", "")),
                name=str(r.get("name", "")),
                code=str(r.get("code", "")),
                line=_int_or_none(r.get("lineNumber")),
                order=_int_or_none(r.get("order")) or 0,
                type_full_name=str(r.get("typeFullName", "")),
            )
            for r in nodes_df.to_dict("records")
        ]
        edges = [
            (int(r["outnode"]), int(r["innode"]), str(r["etype"]))
            for r in edges_df.to_dict("records")
        ]
        return cls(nodes, edges)

    # -- adjacency --------------------------------------------------------
    def _build(self, etype: str) -> None:
        succ: dict[int, list[int]] = defaultdict(list)
        pred: dict[int, list[int]] = defaultdict(list)
        for s, d, e in self.edges:
            if e == etype:
                succ[s].append(d)
                pred[d].append(s)
        self._succ[etype] = succ
        self._pred[etype] = pred

    def successors(self, node: int, etype: str) -> list[int]:
        if etype not in self._succ:
            self._build(etype)
        return self._succ[etype].get(node, [])

    def predecessors(self, node: int, etype: str) -> list[int]:
        if etype not in self._pred:
            self._build(etype)
        return self._pred[etype].get(node, [])

    def edge_nodes(self, etype: str) -> set[int]:
        """All nodes participating in at least one ``etype`` edge."""
        if etype not in self._succ:
            self._build(etype)
        out: set[int] = set()
        out.update(self._succ[etype])
        out.update(self._pred[etype])
        return out

    def edge_arrays(self, etype: str) -> tuple[np.ndarray, np.ndarray]:
        """(src, dst) int32 arrays for one edge type — feeds graph batching."""
        src = [s for s, d, e in self.edges if e == etype]
        dst = [d for s, d, e in self.edges if e == etype]
        return np.array(src, dtype=np.int64), np.array(dst, dtype=np.int64)

    # -- traversal helpers used by the analyses ---------------------------
    def ast_descendants(self, root: int, skip_labels: frozenset[str] = frozenset()) -> list[int]:
        """All AST-reachable nodes below ``root`` (excluding it), skipping
        subtrees rooted at nodes whose label is in ``skip_labels``."""
        out: list[int] = []
        stack = list(self.successors(root, "AST"))
        seen = {root}
        while stack:
            n = stack.pop()
            if n in seen:
                continue
            seen.add(n)
            if n in self.nodes and self.nodes[n].label in skip_labels:
                continue
            out.append(n)
            stack.extend(self.successors(n, "AST"))
        return out

    def arguments(self, call: int) -> dict[int, int]:
        """ARGUMENT successors keyed by their ``order`` (1-based)."""
        return {self.nodes[a].order: a for a in self.successors(call, "ARGUMENT") if a in self.nodes}

    def attr(self, name: str) -> dict[int, Any]:
        return {i: getattr(n, name) for i, n in self.nodes.items()}

    def __len__(self) -> int:
        return len(self.nodes)

    def __repr__(self) -> str:
        kinds = defaultdict(int)
        for _, _, e in self.edges:
            kinds[e] += 1
        return f"CPG({len(self.nodes)} nodes, {dict(kinds)})"


# ---------------------------------------------------------------------------
# edge-type subgraph selection + k-hop neighbourhoods

# gtype → edge types, parity with the reference's ``rdg``
# (``DDFA/sastvd/helpers/joern.py:419-441``). "cfg" is the golden config
# (``configs/config_bigvul.yaml``); REACHING_DEF/CDG come from Joern or from
# ``features.add_dependence_edges`` on natively-extracted graphs.
RDG_ETYPES: dict[str, tuple[str, ...]] = {
    "reftype": ("EVAL_TYPE", "REF"),
    "ast": ("AST",),
    "pdg": ("REACHING_DEF", "CDG"),
    "cfgcdg": ("CFG", "CDG"),
    "cfg": ("CFG",),
    "all": ("REACHING_DEF", "CDG", "AST", "EVAL_TYPE", "REF"),
    "dataflow": ("CFG", "AST"),
}


def rdg(cpg: "CPG", gtype: str) -> list[tuple[int, int]]:
    """Deduped (src, dst) edge list of the ``gtype`` subgraph."""
    etypes = RDG_ETYPES.get(gtype)
    if etypes is None:
        raise ValueError(f"unknown gtype {gtype!r}; known: {sorted(RDG_ETYPES)}")
    return sorted({(s, d) for s, d, e in cpg.edges if e in etypes})


def khop_neighbours(
    cpg: "CPG",
    node_ids: list[int],
    hop: int = 1,
    gtype: str = "all",
    intermediate: bool = True,
) -> dict[int, list[int]]:
    """Neighbours within ``hop`` steps (undirected), via sparse matrix powers
    (parity: ``joern.py:372-416``). ``intermediate=True`` unions hops 1..k;
    otherwise only exactly-k-step neighbours are returned."""
    from scipy import sparse

    edges = rdg(cpg, gtype)
    ids = sorted(cpg.nodes)
    id2adj = {nid: i for i, nid in enumerate(ids)}
    n = len(ids)
    rows, cols = [], []
    for s, d in edges:
        rows += [id2adj[s], id2adj[d]]
        cols += [id2adj[d], id2adj[s]]
    coo = sparse.coo_matrix(
        (np.ones(len(rows)), (rows, cols)), shape=(n, n)
    ).tocsr()
    out: dict[int, list[int]] = {nid: [] for nid in node_ids}
    hops = range(1, hop + 1) if intermediate else [hop]
    for h in hops:
        csr = coo**h
        for nid in node_ids:
            row = csr[id2adj[nid]].toarray()[0].nonzero()[0]
            out[nid] += [ids[i] for i in row]
    return out
