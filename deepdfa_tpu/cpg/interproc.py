"""Interprocedural dataflow: the call-graph supergraph over the gen-kill
framework.

The PR 1 analyses (:mod:`deepdfa_tpu.cpg.analyses`) are strictly
per-function — a vulnerability whose source and sink live in different
functions is structurally invisible to them. This module composes those
same analyses over the call graph, host-side, context-insensitively:

**Supergraph** (:func:`build_supergraph`): a NEW derived :class:`CPG`
(fresh object — per-CPG adjacency caches are never mutated) containing
every original node/edge plus, per resolved call site ``c`` in caller ``f``
to callee ``g``:

- one *parameter-binding* node per callee parameter — a synthetic
  ``<operator>.assignment`` whose lvalue IDENTIFIER is the parameter name
  and whose rvalue IDENTIFIERs are the argument expression's mentions;
  bindings chain ``c → b₁ → … → bₖ → METHOD(g)`` in CFG, so the call edge
  carries facts into the callee through ordinary gen/kill transfer
  (strong kill of the parameter + conditional gen from the argument);
- one *return-binding* node ``r`` with ``METHOD_RETURN(g) → r → succ(c)``
  CFG edges — a pure routing node (no gen/kill) that links the callee's
  exit state back to the call-site result position.

Unresolved externals (library calls, function pointers, malformed names)
contribute nothing — the summarized no-op of :mod:`.callgraph`. The
original intraprocedural CFG edges are all retained, so every analysis here
is a *may* over-approximation that strictly extends the per-function
solution.

**Interprocedural reaching definitions**: :func:`reaching_definitions` run
directly on the supergraph — binding nodes are textually real assignments,
so callee parameters acquire definitions owned by the call site. The
``ireach`` feature family counts, per node, the reaching definitions owned
by a *different* method.

**Interprocedural taint** (:func:`solve_interproc_taint`): facts are
qualified ``"method::var"`` strings so same-named locals in different
functions never conflate. The static instance is the per-function
:func:`~deepdfa_tpu.cpg.analyses._taint_static` qualified node-wise by
owner method, plus the call/return transfer: parameter bindings gen the
callee-qualified parameter from caller-qualified argument mentions; RETURN
nodes of called methods gen a ``"g::<ret>"`` fact from their expression
mentions; call-site assignment statements list ``"g::<ret>"`` among their
RHS mentions, closing the loop through the return edge. Parameter seeding
is restricted to *root* methods (no resolved incoming call edge) — with
zero call edges every method is a root, so the projected solution is
bit-equal to the intraprocedural :func:`solve_taint` fixpoint on every
backend (the parity property ``tests/test_interproc.py`` pins).

**Cross-function findings** (:func:`cross_function_taint`): a node is a
cross-function taint use iff it is tainted under source-API-only
interprocedural taint (no parameter seeds at all) but NOT under the same
analysis confined to its own function — per-function scoring cannot see it
by construction. Attribution walks the call graph back to the
source-API-carrying methods.

All solving goes through the existing ``sets``/``bitvec``/``native``
backends untouched; nothing here runs on an accelerator. GGNN inputs stay
per-function buckets — the ``_DFA_ireach``/``_DFA_itaint`` families
(:func:`interproc_node_features`) annotate nodes, they do not grow graphs.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Sequence

from deepdfa_tpu.cpg import analyses
from deepdfa_tpu.cpg.analyses import (
    DEFAULT_TAINT_SOURCES,
    Problem,
    Solution,
    _subtree,
    _taint_static,
    reaching_definitions,
    solve_bitvec,
)
from deepdfa_tpu.cpg.callgraph import CallGraph, CallSite, build_callgraph, method_owner_map
from deepdfa_tpu.cpg.schema import CPG, Node

__all__ = [
    "RET_FACT",
    "IPROC_ANALYSES",
    "Supergraph",
    "merge_cpgs",
    "build_supergraph",
    "interproc_reaching_definitions",
    "solve_interproc_taint",
    "project_taint",
    "interproc_taint_node_codes",
    "cross_function_taint",
    "interproc_node_features",
    "solve_interproc_analysis",
]

RET_FACT = "<ret>"
BIND_OP = "<operator>.assignment"  # bindings are textually real assignments
RETURN_BIND_OP = "<interproc>.returnBind"  # routing only: no gen, no kill

IPROC_ANALYSES = ("reaching_defs", "taint")


# ------------------------------------------------------------------ merging


def merge_cpgs(cpgs: Sequence[CPG]) -> tuple[CPG, list[dict[int, int]]]:
    """Merge independently-parsed CPGs (overlapping id spaces) into one CPG
    with disjoint ids. Returns the merged graph plus one old→new id map per
    input. Dangling edges (an endpoint missing from the node table) are
    dropped, never KeyError — they are validate's ``dangling-edge`` rows."""
    nodes: list[Node] = []
    edges: list[tuple[int, int, str]] = []
    maps: list[dict[int, int]] = []
    next_base = 0
    for cpg in cpgs:
        ids = sorted(cpg.nodes)
        lo = ids[0] if ids else 0
        idmap = {old: next_base + (old - lo) for old in ids}
        maps.append(idmap)
        for old in ids:
            nodes.append(dataclasses.replace(cpg.nodes[old], id=idmap[old]))
        for s, d, e in cpg.edges:
            ns, nd = idmap.get(s), idmap.get(d)
            if ns is not None and nd is not None:
                edges.append((ns, nd, e))
        if ids:
            next_base += (ids[-1] - lo) + 1000
    return CPG(nodes, edges), maps


# --------------------------------------------------------------- supergraph


@dataclasses.dataclass
class Supergraph:
    """The derived interprocedural CPG plus the bookkeeping the analyses
    need. ``owner`` maps EVERY node (bindings included) to a METHOD id;
    binding nodes belong to their *caller* — the value they carry is caller
    state entering the callee, which is exactly what ``ireach`` counts as
    foreign."""

    base: CPG
    cpg: CPG
    callgraph: CallGraph
    owner: dict[int, int]
    method_names: dict[int, str]
    # bind node id -> (call id, caller METHOD id, callee METHOD id)
    param_binds: dict[int, tuple[int, int, int]]
    return_binds: dict[int, tuple[int, int, int]]
    linked_sites: list[CallSite]

    @property
    def n_call_edges(self) -> int:
        return len(self.linked_sites)

    def owner_name(self, nid: int) -> str:
        mid = self.owner.get(nid)
        return self.method_names.get(mid, "") if mid is not None else ""


def _method_params(cpg: CPG, mid: int) -> list[Node]:
    params = [
        cpg.nodes[d]
        for d in cpg.successors(mid, "AST")
        if d in cpg.nodes and cpg.nodes[d].label == "METHOD_PARAMETER_IN"
    ]
    return sorted(params, key=lambda p: p.order)


def _method_return(cpg: CPG, mid: int) -> int | None:
    for d in cpg.successors(mid, "AST"):
        if d in cpg.nodes and cpg.nodes[d].label == "METHOD_RETURN":
            return d
    return None


def _mention_codes(cpg: CPG, root: int) -> list[str]:
    """IDENTIFIER/CALL codes in ``root``'s subtree (root included) — the
    same textual mention convention as the taint propagation rule."""
    out = []
    for d in _subtree(cpg, root):
        nd = cpg.nodes.get(d)
        if nd is not None and nd.label in ("IDENTIFIER", "CALL") and nd.code:
            out.append(nd.code)
    return sorted(set(out))


def build_supergraph(cpg: CPG) -> Supergraph:
    """Construct the interprocedural supergraph. Total: malformed callee
    references, arity mismatches, missing METHOD_RETURNs and dangling call
    sites all degrade to weaker linking (validate reports them as
    ``call-ref`` rows) — never an exception."""
    owner = method_owner_map(cpg)
    cg = build_callgraph(cpg, owner)
    method_names = {
        n.id: n.name for n in cpg.nodes.values() if n.label == "METHOD"
    }

    nodes = list(cpg.nodes.values())
    edges = list(cpg.edges)
    next_id = (max(cpg.nodes) + 1000) if cpg.nodes else 1
    param_binds: dict[int, tuple[int, int, int]] = {}
    return_binds: dict[int, tuple[int, int, int]] = {}
    linked: list[CallSite] = []
    sg_owner = dict(owner)

    for site in cg.sites:
        if site.callee is None or site.caller is None:
            continue  # summarized external / unattributable: no-op edge
        c, f, g = site.call, site.caller, site.callee
        succs = list(cpg.successors(c, "CFG"))
        if not succs and not cpg.predecessors(c, "CFG"):
            continue  # dead-code call: not in the CFG, nothing to link
        args = cpg.arguments(c)
        params = _method_params(cpg, g)
        gname = method_names.get(g, "")

        prev = c
        for param in params:
            b = next_id
            next_id += 1
            nodes.append(Node(id=b, label="CALL", name=BIND_OP,
                              code=f"{param.name} := <arg {param.order} of {gname}>",
                              line=cpg.nodes[c].line))
            lv = next_id
            next_id += 1
            nodes.append(Node(id=lv, label="IDENTIFIER", name=param.name,
                              code=param.name, order=1))
            edges.append((b, lv, "AST"))
            edges.append((b, lv, "ARGUMENT"))
            arg = args.get(param.order)
            order = 2
            if arg is not None and arg in cpg.nodes:
                for code in _mention_codes(cpg, arg):
                    m = next_id
                    next_id += 1
                    nodes.append(Node(id=m, label="IDENTIFIER", name=code,
                                      code=code, order=order))
                    order += 1
                    edges.append((b, m, "AST"))
                    edges.append((b, m, "ARGUMENT"))
            edges.append((prev, b, "CFG"))
            param_binds[b] = (c, f, g)
            sg_owner[b] = f
            sg_owner[lv] = f
            prev = b
        edges.append((prev, g, "CFG"))  # enter the callee

        mret = _method_return(cpg, g)
        if mret is not None and succs:
            r = next_id
            next_id += 1
            nodes.append(Node(id=r, label="CALL", name=RETURN_BIND_OP,
                              code=f"{RET_FACT} of {gname}",
                              line=cpg.nodes[c].line))
            edges.append((mret, r, "CFG"))
            for s in succs:
                edges.append((r, s, "CFG"))
            return_binds[r] = (c, f, g)
            sg_owner[r] = f
        linked.append(site)

    super_cpg = CPG(nodes, edges)
    # IDENTIFIER children of bindings: owned by the caller like their parent
    for b in param_binds:
        for d in super_cpg.successors(b, "AST"):
            sg_owner.setdefault(d, param_binds[b][1])
    return Supergraph(base=cpg, cpg=super_cpg, callgraph=cg, owner=sg_owner,
                      method_names=method_names, param_binds=param_binds,
                      return_binds=return_binds, linked_sites=linked)


# ---------------------------------------------------- reaching definitions


def interproc_reaching_definitions(sg: Supergraph) -> Problem:
    """Forward-may reaching defs over the supergraph: the PR 1 builder
    verbatim — parameter bindings are textually real assignments, so the
    call transfer needs no special casing. With zero call edges the
    supergraph IS the base CPG and the instance is bit-identical."""
    return reaching_definitions(sg.cpg)


# ------------------------------------------------------------------- taint


def _qual(method: str, fact: str) -> str:
    return f"{method}::{fact}"


def _qualify(method: str, facts) -> set[str]:
    return {_qual(method, f) for f in facts}


def _interproc_taint_static(sg: Supergraph, source_apis: frozenset[str],
                            seed_params: str):
    """The qualified interprocedural taint instance.

    Node-wise qualification of the per-function static instance (a pure
    fact rename, so per-node transfer is EXACTLY the PR 1 semantics), plus
    the call/return machinery described in the module docstring.
    ``seed_params``: "roots" (default analysis), "all" (degenerates to the
    per-function seeding) or "none" (source APIs only — the cross-function
    finding baseline)."""
    cpg = sg.cpg
    facts_u, gen_u, kill_u, dv_u, dr_u = _taint_static(cpg, source_apis)

    roots = sg.callgraph.root_methods()
    called = {s.callee for s in sg.linked_sites}

    facts: set[str] = set()
    base_gen: dict[int, set] = {}
    kill: dict[int, set] = {}
    def_var: dict[int, str] = {}
    def_rhs: dict[int, set[str]] = {}

    for n in gen_u:
        node = cpg.nodes.get(n)
        if n in sg.return_binds:
            base_gen[n], kill[n] = set(), set()
            continue
        if n in sg.param_binds:
            _, fmid, gmid = sg.param_binds[n]
            fname = sg.method_names.get(fmid, "")
            gname = sg.method_names.get(gmid, "")
            base_gen[n] = _qualify(gname, gen_u.get(n, ()))
            kill[n] = _qualify(gname, kill_u.get(n, ()))
            if n in dv_u:
                def_var[n] = _qual(gname, dv_u[n])
                def_rhs[n] = _qualify(fname, dr_u.get(n, ()))
            continue
        mname = sg.owner_name(n)
        gens = gen_u.get(n, set())
        if (node is not None and node.label == "METHOD"
                and seed_params != "all"):
            if seed_params == "none" or n not in roots:
                gens = set()  # params bound at call sites (or unseeded)
        base_gen[n] = _qualify(mname, gens)
        kill[n] = _qualify(mname, kill_u.get(n, ()))
        if n in dv_u:
            def_var[n] = _qual(mname, dv_u[n])
            def_rhs[n] = _qualify(mname, dr_u.get(n, ()))

    # RETURN nodes of called methods define "g::<ret>" from their expression
    # mentions; confined to call targets so a zero-call-edge supergraph adds
    # no machinery at all (the parity property).
    cfg_nodes = set(base_gen)
    for n in cfg_nodes:
        node = cpg.nodes.get(n)
        if node is None or node.label != "RETURN":
            continue
        mid = sg.owner.get(n)
        if mid not in called:
            continue
        gname = sg.method_names.get(mid, "")
        def_var.setdefault(n, _qual(gname, RET_FACT))
        mentions = set(_mention_codes(cpg, n))
        mentions.discard(node.code)
        def_rhs[n] = def_rhs.get(n, set()) | _qualify(gname, mentions)

    # call-site result: an assignment whose subtree holds a resolved call
    # reads "g::<ret>" (routed to it via the return-binding CFG edge)
    callee_of = {s.call: s.callee for s in sg.linked_sites}
    for n, var in list(dv_u.items()):
        if n in sg.param_binds or n not in cfg_nodes:
            continue
        for d in _subtree(cpg, n):
            g = callee_of.get(d)
            if g is not None:
                gname = sg.method_names.get(g, "")
                def_rhs.setdefault(n, set()).add(_qual(gname, RET_FACT))

    for s in base_gen.values():
        facts |= s
    for s in kill.values():
        facts |= s
    facts |= set(def_var.values())
    for s in def_rhs.values():
        facts |= s
    return tuple(sorted(facts)), base_gen, kill, def_var, def_rhs


def _outer_taint_solve(cpg: CPG, static, solver) -> Solution:
    """solve_taint's conditional-gen outer iteration over an explicit
    static instance (gens only grow ⇒ terminates; every backend reaches
    the same fixpoint)."""
    facts, base_gen, kill, def_var, def_rhs = static
    extra: dict[int, set] = {n: set() for n in base_gen}
    while True:
        gen = {n: base_gen[n] | extra[n] for n in base_gen}
        sol = solver(Problem(cpg, "forward", "may", facts, gen, kill,
                             name="interproc_taint"))
        changed = False
        for n, var in def_var.items():
            if var in gen.get(n, set()):
                continue
            if def_rhs.get(n, set()) & sol.in_facts.get(n, set()):
                extra.setdefault(n, set()).add(var)
                changed = True
        if not changed:
            return sol


def solve_interproc_taint(
    sg: Supergraph,
    source_apis: frozenset[str] = DEFAULT_TAINT_SOURCES,
    solver: Callable[[Problem], Solution] = solve_bitvec,
    seed_params: str = "roots",
) -> Solution:
    """Context-insensitive interprocedural taint over the supergraph.
    Facts are ``"method::var"`` qualified; :func:`project_taint` recovers
    the per-function view."""
    if seed_params not in ("roots", "all", "none"):
        raise ValueError(f"seed_params must be roots|all|none, got {seed_params!r}")
    static = _interproc_taint_static(sg, source_apis, seed_params)
    return _outer_taint_solve(sg.cpg, static, solver)


def project_taint(sg: Supergraph, sol: Solution) -> Solution:
    """Per-function view of a qualified solution: restrict to the base
    CPG's nodes, keep each node's own-method facts, strip the qualifier
    and the synthetic ``<ret>`` fact."""
    def proj(table: dict[int, set]) -> dict[int, set]:
        out: dict[int, set] = {}
        for n, fs in table.items():
            if n not in sg.base.nodes:
                continue
            prefix = sg.owner_name(n) + "::"
            out[n] = {
                f[len(prefix):] for f in fs
                if f.startswith(prefix) and f[len(prefix):] != RET_FACT
            }
        return out

    return Solution(proj(sol.in_facts), proj(sol.out_facts))


def _codes_from(sg: Supergraph, sol: Solution, kill: dict[int, set]) -> dict[int, int]:
    """taint_node_codes semantics over qualified facts, original nodes only:
    0 untouched / 1 uses / 2 introduces."""
    cpg = sg.cpg
    out: dict[int, int] = {}
    for n, in_facts in sol.in_facts.items():
        if n not in sg.base.nodes:
            continue
        gens = sol.out_facts.get(n, set()) - (in_facts - kill.get(n, set()))
        if gens:
            out[n] = 2
            continue
        mname = sg.owner_name(n)
        mentions = _qualify(mname, _mention_codes(cpg, n))
        out[n] = 1 if mentions & in_facts else 0
    return out


def interproc_taint_node_codes(
    sg: Supergraph,
    source_apis: frozenset[str] = DEFAULT_TAINT_SOURCES,
    solver: Callable[[Problem], Solution] = solve_bitvec,
    seed_params: str = "roots",
) -> dict[int, int]:
    """Per-node interprocedural taint code (0/1/2) over the base nodes."""
    static = _interproc_taint_static(sg, source_apis, seed_params)
    sol = _outer_taint_solve(sg.cpg, static, solver)
    return _codes_from(sg, sol, static[2])


def cross_function_taint(
    sg: Supergraph,
    source_apis: frozenset[str] = DEFAULT_TAINT_SOURCES,
    solver: Callable[[Problem], Solution] = solve_bitvec,
) -> dict:
    """Nodes tainted ONLY when taint may cross a call boundary.

    Baseline: source-API-only taint confined to each function (no
    parameter seeds, no call edges — what per-function scoring sees).
    Interprocedural: the same seeds propagated through the supergraph.
    Every node flagged here is structurally invisible per-function.

    Returns ``{"nodes": {nid: inter_code}, "findings": [row...],
    "attribution": {method: [source methods]}}``.
    """
    inter = interproc_taint_node_codes(sg, source_apis, solver,
                                       seed_params="none")

    intra_static = _taint_static(sg.base, source_apis)
    facts_u, gen_u, kill_u, dv_u, dr_u = intra_static
    stripped = {
        n: (set() if (sg.base.nodes.get(n) is not None
                      and sg.base.nodes[n].label == "METHOD") else s)
        for n, s in gen_u.items()
    }
    intra_sol = _outer_taint_solve(
        sg.base, (facts_u, stripped, kill_u, dv_u, dr_u), solver)
    intra_codes: dict[int, int] = {}
    for n, in_facts in intra_sol.in_facts.items():
        gens = intra_sol.out_facts.get(n, set()) - (in_facts - kill_u.get(n, set()))
        if gens:
            intra_codes[n] = 2
            continue
        mentions = set(_mention_codes(sg.base, n))
        intra_codes[n] = 1 if mentions & in_facts else 0

    cross = {n: c for n, c in inter.items()
             if c >= 1 and intra_codes.get(n, 0) == 0}

    # attribution: source-API-carrying methods connected to the finding's
    # method in the (undirected) call graph — taint travels caller→callee
    # through params and callee→caller through returns
    source_methods: set[int] = set()
    for n in sg.base.nodes.values():
        if n.label == "CALL" and n.name in source_apis:
            mid = sg.owner.get(n.id)
            if mid is not None:
                source_methods.add(mid)
    adj: dict[int, set[int]] = {}
    for a, b in sg.callgraph.edges:
        adj.setdefault(a, set()).add(b)
        adj.setdefault(b, set()).add(a)

    def reachable(start: int) -> set[int]:
        seen = {start}
        stack = [start]
        while stack:
            for nxt in adj.get(stack.pop(), ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append(nxt)
        return seen

    attribution: dict[str, list[str]] = {}
    findings = []
    for nid in sorted(cross):
        node = sg.base.nodes[nid]
        mid = sg.owner.get(nid)
        mname = sg.method_names.get(mid, "") if mid is not None else ""
        carriers = sorted(
            sg.method_names.get(m, "")
            for m in (source_methods & reachable(mid) if mid is not None else set())
            if m != mid
        )
        if mname and carriers:
            attribution[mname] = sorted(
                set(attribution.get(mname, [])) | set(carriers))
        findings.append({
            "node": nid,
            "function": mname,
            "line": node.line,
            "code": node.code,
            "taint": cross[nid],
            "sources": carriers,
            "kind": "cross-function-taint",
        })
    return {"nodes": cross, "findings": findings, "attribution": attribution}


# ------------------------------------------------------------ feature view


def interproc_node_features(cpg: CPG, sg: Supergraph | None = None
                            ) -> dict[str, dict[int, int]]:
    """``{"ireach": {node: count}, "itaint": {node: code}}`` over the base
    CPG's nodes — the ``_DFA_ireach``/``_DFA_itaint`` feature families.

    ``ireach``: reaching definitions owned by a different method (call-site
    bindings count as the caller's), the raw interprocedural fan-in signal;
    clipped downstream by ``DFA_FEATURE_DIMS``. ``itaint``: the taint code
    (0/1/2) under root-seeded interprocedural taint, escalated to 3 on
    nodes only a cross-boundary flow can taint. On a single-function CPG
    (zero call edges) ireach is all-zero and itaint equals ``_DFA_taint``
    — the families strictly extend, never perturb, the PR 1 ones.

    ``sg``: an already-built supergraph of ``cpg`` — callers that hold one
    (the scan's interproc pass, the hierarchical scorer's summary builder)
    pass it to skip the rebuild; semantics are identical.
    """
    from deepdfa_tpu.cpg.analyses import solve_native

    if sg is None:
        sg = build_supergraph(cpg)
    rd_sol = solve_native(interproc_reaching_definitions(sg))
    ireach: dict[int, int] = {}
    for n, in_facts in rd_sol.in_facts.items():
        if n not in sg.base.nodes:
            continue
        mine = sg.owner.get(n)
        ireach[n] = sum(1 for d in in_facts if sg.owner.get(d.node) != mine)

    itaint = interproc_taint_node_codes(sg, solver=solve_native)
    if sg.linked_sites:
        for n in cross_function_taint(sg, solver=solve_native)["nodes"]:
            itaint[n] = 3
    return {"ireach": ireach, "itaint": itaint}


# ------------------------------------------------------------ uniform entry


def solve_interproc_analysis(name: str, cpg: CPG,
                             backend: str = "bitvec") -> Solution:
    """Uniform entry mirroring :func:`analyses.solve_analysis`: build the
    supergraph, solve interprocedurally, return the per-function projection
    (original nodes; taint facts unqualified) — directly comparable to the
    intraprocedural solution, and bit-equal to it when the CPG has zero
    call edges."""
    if name not in IPROC_ANALYSES:
        raise ValueError(f"unknown interprocedural analysis {name!r}; "
                         f"known: {IPROC_ANALYSES}")
    solver = analyses._BACKENDS[backend]
    sg = build_supergraph(cpg)
    if name == "reaching_defs":
        sol = solver(interproc_reaching_definitions(sg))
        keep = set(sg.base.nodes)
        return Solution(
            {n: s for n, s in sol.in_facts.items() if n in keep},
            {n: s for n, s in sol.out_facts.items() if n in keep},
        )
    sol = solve_interproc_taint(sg, solver=solver)
    return project_taint(sg, sol)
