"""Reaching-definitions analysis — the "DFA" in DeepDFA.

Semantics match the reference's executable spec
(``DDFA/code_gnn/analysis/dataflow.py:60-177``), which itself mirrors Joern's
``default.semantics`` operator model:

- a node **generates** a definition iff it is a call named by one of the 18
  assignment / inc-dec operators (both ``<operator>.*`` and the ``<operators>``
  spelling Joern sometimes emits — the quirk covered by the reference's
  ``test_weird_assignment_operators``);
- the defined variable is the ``code`` of the call's first ARGUMENT child
  (lowest ``order``);
- a definition of ``v`` **kills** every other definition of ``v``;
- MOP fixpoint over the CFG via a chaotic-iteration worklist.

Reaching definitions is now the first *client* of the generic monotone
framework in :mod:`deepdfa_tpu.cpg.analyses` rather than the owner of the
solver machinery: the operator model and the three backends (Python sets /
NumPy bit-matrix / C++ CSR worklist) live there, and this module keeps the
historical API on top — :meth:`ReachingDefinitions.solve`,
:func:`solve_bitvec`, :func:`solve_native` — with unchanged return contracts
(cross-checked in tests). The static gen/kill formulation is equivalent to
the reference's dynamic ``kill(n, in_n)``: removing only the *reaching* other
defs of ``v`` from ``in_n`` equals removing all of them.
"""

from __future__ import annotations

from deepdfa_tpu.cpg import analyses
from deepdfa_tpu.cpg.analyses import (
    ASSIGNMENT_OPS,
    INC_DEC_OPS,
    MOD_OPS,
    Problem,
    VariableDefinition,
    reaching_definitions,
)
from deepdfa_tpu.cpg.schema import CPG

__all__ = [
    "ASSIGNMENT_OPS",
    "INC_DEC_OPS",
    "MOD_OPS",
    "VariableDefinition",
    "ReachingDefinitions",
    "solve_bitvec",
    "solve_native",
]


class ReachingDefinitions:
    """Gen/kill construction + solver entry points over a CPG's CFG."""

    def __init__(self, cpg: CPG):
        self.cpg = cpg
        self.cfg_nodes = sorted(cpg.edge_nodes("CFG"))
        self.gen: dict[int, set[VariableDefinition]] = {}
        for nid in cpg.nodes:
            var = self.assigned_variable(nid)
            if var is not None:
                self.gen[nid] = {
                    VariableDefinition(var, nid, cpg.nodes[nid].code)
                }
            else:
                self.gen[nid] = set()

    @property
    def domain(self) -> set[VariableDefinition]:
        return set().union(*self.gen.values()) if self.gen else set()

    def assigned_variable(self, nid: int) -> str | None:
        """The defined variable's source text, or None (first ARGUMENT child
        by ``order`` of a mod-op call; textual, handles ``*p``, ``a[i]``)."""
        return analyses.assigned_variable(self.cpg, nid)

    def kill(self, nid: int, defs: set[VariableDefinition]) -> set[VariableDefinition]:
        var = self.assigned_variable(nid)
        if var is None:
            return set()
        return {d for d in defs if d.var == var and d.node != nid}

    def to_problem(self) -> Problem:
        """The framework formulation of this instance (forward-may)."""
        return reaching_definitions(self.cpg)

    def solve(self) -> tuple[dict[int, set], dict[int, set]]:
        """Worklist MOP fixpoint; returns (in_sets, out_sets) of
        :class:`VariableDefinition` keyed by CFG node."""
        sol = analyses.solve_sets(self.to_problem())
        return sol.in_facts, sol.out_facts

    def __str__(self):
        dom = self.domain
        return f"{len(dom)} defs: {sorted(d.code for d in dom)}"


def _as_ids(sets: dict[int, set]) -> dict[int, set[int]]:
    return {nid: {d.node for d in s} for nid, s in sets.items()}


def solve_bitvec(rd: ReachingDefinitions):
    """NumPy bit-matrix worklist; returns (in_sets, out_sets) as
    {node_id: set[def_node_id]}."""
    sol = analyses.solve_bitvec(rd.to_problem())
    return _as_ids(sol.in_facts), _as_ids(sol.out_facts)


def solve_native(rd: ReachingDefinitions):
    """C++ worklist solver; identical output contract to :func:`solve_bitvec`.
    Falls back to the bit-vector solver (one warning) on toolchain-less
    machines — see :func:`deepdfa_tpu.cpg.analyses.solve_native`."""
    sol = analyses.solve_native(rd.to_problem())
    if not sol.in_facts and not sol.out_facts:
        return {}, {}
    return _as_ids(sol.in_facts), _as_ids(sol.out_facts)
