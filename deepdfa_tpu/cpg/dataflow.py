"""Reaching-definitions analysis — the "DFA" in DeepDFA.

Semantics match the reference's executable spec
(``DDFA/code_gnn/analysis/dataflow.py:60-177``), which itself mirrors Joern's
``default.semantics`` operator model:

- a node **generates** a definition iff it is a call named by one of the 18
  assignment / inc-dec operators (both ``<operator>.*`` and the ``<operators>``
  spelling Joern sometimes emits — the quirk covered by the reference's
  ``test_weird_assignment_operators``);
- the defined variable is the ``code`` of the call's first ARGUMENT child
  (lowest ``order``);
- a definition of ``v`` **kills** every other definition of ``v``;
- MOP fixpoint over the CFG via a chaotic-iteration worklist.

Three solvers, one semantics (cross-checked in tests):

1. :meth:`ReachingDefinitions.solve` — reference-shaped Python sets worklist.
2. :func:`solve_bitvec` — NumPy bit-matrix worklist (defs as bit positions).
3. :func:`solve_native` — C++ worklist over CSR arrays
   (``native/dfa_solver.cpp``) via ctypes; the throughput path for corpus
   preprocessing, where the reference leaned on the JVM.
"""

from __future__ import annotations

import ctypes
import dataclasses
import subprocess
from pathlib import Path

import numpy as np

from deepdfa_tpu.cpg.schema import CPG

__all__ = [
    "ASSIGNMENT_OPS",
    "INC_DEC_OPS",
    "MOD_OPS",
    "VariableDefinition",
    "ReachingDefinitions",
    "solve_bitvec",
    "solve_native",
]

ASSIGNMENT_OPS = tuple(
    "<operator>." + n
    for n in (
        "assignment",
        "assignmentAnd",
        "assignmentArithmeticShiftRight",
        "assignmentDivision",
        "assignmentExponentiation",
        "assignmentLogicalShiftRight",
        "assignmentMinus",
        "assignmentModulo",
        "assignmentMultiplication",
        "assignmentOr",
        "assignmentPlus",
        "assignmentShiftLeft",
        "assignmentXor",
    )
)
INC_DEC_OPS = tuple(
    "<operator>." + n
    for n in ("incBy", "postDecrement", "postIncrement", "preDecrement", "preIncrement")
)
# Joern emits "<operators>" for some programs; accept both spellings.
MOD_OPS = frozenset(
    ASSIGNMENT_OPS
    + INC_DEC_OPS
    + tuple(op.replace("<operator>", "<operators>") for op in ASSIGNMENT_OPS + INC_DEC_OPS)
)


@dataclasses.dataclass(frozen=True)
class VariableDefinition:
    var: str
    node: int
    code: str = ""

    def __hash__(self):
        return self.node

    def __eq__(self, other):
        return self.node == other.node


class ReachingDefinitions:
    """Gen/kill construction + Python worklist solver over a CPG's CFG."""

    def __init__(self, cpg: CPG):
        self.cpg = cpg
        self.cfg_nodes = sorted(cpg.edge_nodes("CFG"))
        self.gen: dict[int, set[VariableDefinition]] = {}
        for nid in cpg.nodes:
            var = self.assigned_variable(nid)
            if var is not None:
                self.gen[nid] = {
                    VariableDefinition(var, nid, cpg.nodes[nid].code)
                }
            else:
                self.gen[nid] = set()

    @property
    def domain(self) -> set[VariableDefinition]:
        return set().union(*self.gen.values()) if self.gen else set()

    def assigned_variable(self, nid: int) -> str | None:
        """The defined variable's source text, or None.

        First ARGUMENT child by ``order`` of a mod-op call; the child's
        ``code`` is the variable expression (handles ``*p``, ``a[i]`` the way
        the reference does — textually).
        """
        node = self.cpg.nodes.get(nid)
        if node is None or node.name not in MOD_OPS:
            return None
        args = self.cpg.arguments(nid)
        if not args:
            return None
        first = args[min(args)]
        return self.cpg.nodes[first].code if first in self.cpg.nodes else None

    def kill(self, nid: int, defs: set[VariableDefinition]) -> set[VariableDefinition]:
        var = self.assigned_variable(nid)
        if var is None:
            return set()
        return {d for d in defs if d.var == var and d.node != nid}

    def solve(self) -> tuple[dict[int, set], dict[int, set]]:
        """Worklist MOP fixpoint; returns (in_sets, out_sets) keyed by CFG node."""
        out_sets: dict[int, set] = {n: set() for n in self.cfg_nodes}
        in_sets: dict[int, set] = {n: set() for n in self.cfg_nodes}
        work = list(self.cfg_nodes)
        while work:
            n = work.pop()
            in_n: set = set()
            for p in self.cpg.predecessors(n, "CFG"):
                in_n |= out_sets.get(p, set())
            in_sets[n] = in_n
            new_out = self.gen.get(n, set()) | (in_n - self.kill(n, in_n))
            if new_out != out_sets[n]:
                work.extend(self.cpg.successors(n, "CFG"))
            out_sets[n] = new_out
        return in_sets, out_sets

    def __str__(self):
        dom = self.domain
        return f"{len(dom)} defs: {sorted(d.code for d in dom)}"


def _encode_problem(rd: ReachingDefinitions):
    """Index CFG nodes and definitions; build CSR predecessors and gen/kill
    bit masks shared by the vectorised and native solvers."""
    nodes = rd.cfg_nodes
    idx = {n: i for i, n in enumerate(nodes)}
    defs = sorted(rd.domain, key=lambda d: d.node)
    didx = {d.node: j for j, d in enumerate(defs)}
    n, m = len(nodes), len(defs)

    gen = np.zeros((n, m), dtype=bool)
    kill = np.zeros((n, m), dtype=bool)
    by_var: dict[str, list[int]] = {}
    for j, d in enumerate(defs):
        by_var.setdefault(d.var, []).append(j)
    for nid in nodes:
        i = idx[nid]
        for d in rd.gen.get(nid, ()):
            gen[i, didx[d.node]] = True
        var = rd.assigned_variable(nid)
        if var is not None:
            for j in by_var.get(var, ()):
                if defs[j].node != nid:
                    kill[i, j] = True

    preds_list = [[idx[p] for p in rd.cpg.predecessors(nid, "CFG") if p in idx] for nid in nodes]
    succs_list = [[idx[s] for s in rd.cpg.successors(nid, "CFG") if s in idx] for nid in nodes]
    return nodes, defs, gen, kill, preds_list, succs_list


def solve_bitvec(rd: ReachingDefinitions):
    """NumPy bit-matrix worklist; returns (in_sets, out_sets) as
    {node_id: set[def_node_id]}."""
    nodes, defs, gen, kill, preds, succs = _encode_problem(rd)
    n, m = gen.shape
    out = np.zeros((n, m), dtype=bool)
    inn = np.zeros((n, m), dtype=bool)
    work = list(range(n))
    in_work = [True] * n
    while work:
        i = work.pop()
        in_work[i] = False
        if preds[i]:
            x = np.logical_or.reduce(out[preds[i]], axis=0)
        else:
            x = np.zeros(m, dtype=bool)
        inn[i] = x
        new_out = gen[i] | (x & ~kill[i])
        if not np.array_equal(new_out, out[i]):
            out[i] = new_out
            for s in succs[i]:
                if not in_work[s]:
                    work.append(s)
                    in_work[s] = True
    def_ids = np.array([d.node for d in defs], dtype=np.int64)
    to_sets = lambda mat: {
        nid: set(def_ids[mat[i]].tolist()) for i, nid in enumerate(nodes)
    }
    return to_sets(inn), to_sets(out)


# ---------------------------------------------------------------- native --

_LIB: ctypes.CDLL | None = None


def _native_lib() -> ctypes.CDLL:
    global _LIB
    if _LIB is not None:
        return _LIB
    root = Path(__file__).resolve().parent.parent.parent / "native"
    so = root / "libdfa_solver.so"
    if not (root / "dfa_solver.cpp").exists():
        raise RuntimeError(
            "the C++ reaching-definitions solver needs a source checkout "
            f"(native/dfa_solver.cpp not found under {root}); installed-"
            "package users: call rd.solve() (Python sets) or solve_bitvec "
            "instead — identical fixpoints, cross-checked by the test suite"
        )
    # Always invoke make: it is a no-op when up to date and rebuilds after
    # source edits (a stale .so would otherwise be loaded silently).
    subprocess.run(["make", "-C", str(root), "-s"], check=True)
    lib = ctypes.CDLL(str(so))
    lib.solve_reaching_defs.restype = ctypes.c_int
    lib.solve_reaching_defs.argtypes = [
        ctypes.c_int32,  # n_nodes
        ctypes.c_int32,  # n_defs
        ctypes.POINTER(ctypes.c_int32),  # pred_indptr [n+1]
        ctypes.POINTER(ctypes.c_int32),  # pred_indices
        ctypes.POINTER(ctypes.c_int32),  # succ_indptr [n+1]
        ctypes.POINTER(ctypes.c_int32),  # succ_indices
        ctypes.POINTER(ctypes.c_uint64),  # gen  [n * words]
        ctypes.POINTER(ctypes.c_uint64),  # kill [n * words]
        ctypes.POINTER(ctypes.c_uint64),  # out: in  [n * words]
        ctypes.POINTER(ctypes.c_uint64),  # out: out [n * words]
    ]
    _LIB = lib
    return lib


def _pack_bits(mat: np.ndarray) -> np.ndarray:
    """bool [n, m] → uint64 [n, ceil(m/64)] little-endian bit packing."""
    n, m = mat.shape
    words = max((m + 63) // 64, 1)
    padded = np.zeros((n, words * 64), dtype=bool)
    padded[:, :m] = mat
    b = np.packbits(padded, axis=1, bitorder="little")
    return b.reshape(n, words, 8).view(np.uint64).reshape(n, words)


def _unpack_bits(packed: np.ndarray, m: int) -> np.ndarray:
    n, words = packed.shape
    bytes_ = packed.reshape(n, words, 1).view(np.uint8).reshape(n, words * 8)
    bits = np.unpackbits(bytes_, axis=1, bitorder="little")
    return bits[:, :m].astype(bool)


def _csr(lists: list[list[int]]) -> tuple[np.ndarray, np.ndarray]:
    indptr = np.zeros(len(lists) + 1, dtype=np.int32)
    for i, l in enumerate(lists):
        indptr[i + 1] = indptr[i] + len(l)
    indices = np.concatenate([np.array(l, dtype=np.int32) for l in lists]) if any(lists) else np.zeros(0, np.int32)
    return indptr, indices


def solve_native(rd: ReachingDefinitions):
    """C++ worklist solver; identical output contract to :func:`solve_bitvec`."""
    nodes, defs, gen, kill, preds, succs = _encode_problem(rd)
    n, m = gen.shape
    if n == 0:
        return {}, {}
    words = max((m + 63) // 64, 1)
    gen_p = np.ascontiguousarray(_pack_bits(gen))
    kill_p = np.ascontiguousarray(_pack_bits(kill))
    in_p = np.zeros((n, words), dtype=np.uint64)
    out_p = np.zeros((n, words), dtype=np.uint64)
    pp, pi = _csr(preds)
    sp, si = _csr(succs)

    lib = _native_lib()
    u64p = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))
    i32p = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
    rc = lib.solve_reaching_defs(
        n, m, i32p(pp), i32p(pi), i32p(sp), i32p(si),
        u64p(gen_p), u64p(kill_p), u64p(in_p), u64p(out_p),
    )
    if rc != 0:
        raise RuntimeError(f"native solver failed with rc={rc}")
    def_ids = np.array([d.node for d in defs], dtype=np.int64)
    inn = _unpack_bits(in_p, m)
    out = _unpack_bits(out_p, m)
    to_sets = lambda mat: {
        nid: set(def_ids[mat[i]].tolist()) for i, nid in enumerate(nodes)
    }
    return to_sets(inn), to_sets(out)
