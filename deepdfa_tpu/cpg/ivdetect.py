"""IVDetect per-statement features + the dataset-wide statement-labels cache.

Parity targets (reference, ``DDFA/sastvd/helpers/evaluate.py``):

- ``feature_extraction`` (``:19-191``): per-line feature records — tokenised
  subtoken sequence, line-local AST subgraph, variable name/type pairs, and
  data/control dependency context — plus line-level PDG edges.
- ``get_dep_add_lines_bigvul`` (``:239-255``): the corpus-wide
  ``statement_labels.pkl`` cache mapping function id → removed lines +
  dependent-added lines.

Re-designed for the columnar :class:`~deepdfa_tpu.cpg.schema.CPG` (one node
table + typed edge list) instead of the reference's pandas node/edge frames;
the dependency context comes from the framework's own REACHING_DEF/CDG edges
(native solver, ``cpg/features.add_dependence_edges``) rather than Joern's.
"""

from __future__ import annotations

import pickle
from pathlib import Path
from typing import Any, Callable, Iterable, Mapping, Sequence

from deepdfa_tpu.cpg.schema import CPG
from deepdfa_tpu.data.tokenise import tokenise
from deepdfa_tpu.resilience.journal import atomic_write_bytes

__all__ = [
    "line_dependency_context",
    "feature_extraction",
    "statement_labels",
]


def line_dependency_context(cpg: CPG) -> tuple[dict[int, set[int]], dict[int, set[int]]]:
    """(data, control): per-line dependency neighbour sets.

    REACHING_DEF edges become the DDG context, CDG edges the control context
    (``evaluate.py:142-171``): projected onto line numbers, symmetrised
    (the reference concatenates the reversed edge list), self-loops dropped.
    """
    line_of = {i: n.line for i, n in cpg.nodes.items() if n.line is not None}
    data: dict[int, set[int]] = {}
    control: dict[int, set[int]] = {}
    for s, d, e in cpg.edges:
        ctx = data if e == "REACHING_DEF" else control if e == "CDG" else None
        if ctx is None:
            continue
        ls, ld = line_of.get(s), line_of.get(d)
        if ls is None or ld is None or ls == ld:
            continue
        ctx.setdefault(ls, set()).add(ld)
        ctx.setdefault(ld, set()).add(ls)
    return data, control


def _line_nodes(cpg: CPG) -> dict[int, list[int]]:
    """line → node ids on that line, in id order (the per-line index the AST
    sub-graphs are expressed in; reference ``cumcount`` over the node table)."""
    by_line: dict[int, list[int]] = {}
    for i in sorted(cpg.nodes):
        n = cpg.nodes[i]
        if n.line is not None:
            by_line.setdefault(n.line, []).append(i)
    return by_line


def _subseq(cpg: CPG, nodes_on_line: Sequence[int]) -> str:
    """Tokenised code of the line: the longest-code node on the line (the
    statement root — reference picks it the same way, ``:53-66``), prefixed
    with the declared local's type when the line declares one."""
    best = max(nodes_on_line, key=lambda i: len(cpg.nodes[i].code), default=None)
    if best is None:
        return ""
    local_type = next(
        (cpg.nodes[i].type_full_name for i in nodes_on_line
         if cpg.nodes[i].label == "LOCAL" and cpg.nodes[i].type_full_name),
        "",
    )
    return tokenise(f"{local_type} {cpg.nodes[best].code}".strip())


def _line_ast(
    cpg: CPG, line: int, nodes_on_line: Sequence[int]
) -> list[list[Any]]:
    """``[outnodes, innodes, token_lists]`` of the line-local AST in per-line
    indices, with lone nodes and parent roots re-wired under index 0 so the
    sub-graph is connected (``evaluate.py:69-103``)."""
    idx = {nid: k for k, nid in enumerate(nodes_on_line)}
    outs: list[int] = []
    ins: list[int] = []
    for s, d, e in cpg.edges:
        if e == "AST" and s in idx and d in idx:
            outs.append(idx[s])
            ins.append(idx[d])
    lone = [k for nid, k in idx.items() if k not in outs and k not in ins]
    parents = [k for k in outs if k not in ins]
    for k in sorted(set(lone + parents) - {0}):
        outs.append(0)
        ins.append(k)
    codes = [tokenise(cpg.nodes[nid].code) for nid in nodes_on_line]
    return [outs, ins, codes]


def _nametypes(cpg: CPG, nodes_on_line: Sequence[int]) -> str:
    """Tokenised ``type name`` pairs of identifiers/declarations on the line
    (``evaluate.py:105-123`` builds these from Joern's REF/TYPE component;
    natively the types are already resolved on the nodes)."""
    pairs: list[str] = []
    seen: set[tuple[str, str]] = set()
    for i in nodes_on_line:
        n = cpg.nodes[i]
        if n.label not in ("IDENTIFIER", "LOCAL", "METHOD_PARAMETER_IN"):
            continue
        if not n.name or not n.type_full_name:
            continue
        key = (n.type_full_name, n.name)
        if key in seen:
            continue
        seen.add(key)
        pairs.append(f"{tokenise(n.type_full_name)} {tokenise(n.name)}".strip())
    return " ".join(p for p in pairs if p)


def feature_extraction(
    cpg: CPG,
    cache_dir: str | Path | None = None,
    key: str | None = None,
) -> tuple[list[dict[str, Any]], tuple[list[int], list[int]]]:
    """IVDetect code representation of one function.

    Returns ``(rows, pdg_edges)``: ``rows`` is one record per PDG line —
    ``{"line", "subseq", "ast", "nametypes", "data", "control"}`` sorted by
    line — and ``pdg_edges`` is ``(outnode_idxs, innode_idxs)`` between row
    indices (the reference's ``pdg_nodes``/``pdg_edges`` pair, ``:172-190``).

    Lines participating in no data/control dependency are dropped, like the
    reference's ``drop_lone_nodes`` on the line-level PDG. ``cache_dir``+
    ``key`` enable the per-function pickle cache (``:40-46``).
    """
    cachefp = None
    if cache_dir is not None and key is not None:
        cachefp = Path(cache_dir) / f"{key}.pkl"
        if cachefp.exists():
            try:
                with open(cachefp, "rb") as f:
                    return pickle.load(f)
            except Exception:  # noqa: BLE001 — corrupt cache: recompute
                pass

    data, control = line_dependency_context(cpg)
    by_line = _line_nodes(cpg)
    pdg_lines = sorted(set(data) | set(control))

    rows: list[dict[str, Any]] = []
    for line in pdg_lines:
        nodes_on_line = by_line.get(line, [])
        rows.append(
            {
                "line": line,
                "subseq": _subseq(cpg, nodes_on_line),
                "ast": _line_ast(cpg, line, nodes_on_line),
                "nametypes": _nametypes(cpg, nodes_on_line),
                "data": sorted(data.get(line, ())),
                "control": sorted(control.get(line, ())),
            }
        )
    row_idx = {r["line"]: k for k, r in enumerate(rows)}
    pairs: set[tuple[int, int]] = set()  # dedupe data+control-coupled pairs
    for line, neighbours in list(data.items()) + list(control.items()):
        for other in neighbours:
            if line in row_idx and other in row_idx:
                pairs.add((row_idx[line], row_idx[other]))
    ordered = sorted(pairs)
    result = (rows, ([p[0] for p in ordered], [p[1] for p in ordered]))

    if cachefp is not None:
        Path(cache_dir).mkdir(parents=True, exist_ok=True)
        atomic_write_bytes(cachefp, pickle.dumps(result))
    return result


def statement_labels(
    records: Iterable[Mapping[str, Any]],
    cpgs: Mapping[int, CPG],
    parse: Callable[[str], CPG],
    cache_path: str | Path | None = None,
    cache: bool = True,
) -> dict[int, dict[str, list[int]]]:
    """Corpus-wide statement labels: ``{id: {"removed": [...], "depadd": [...]}}``.

    ``statement_labels.pkl`` parity (``evaluate.py:239-255``): computed once
    for the vulnerable rows (removed lines straight from the diff labeler,
    dependent-added lines via :func:`~deepdfa_tpu.cpg.features.dep_add_lines`
    on the before/after CPG pair) and pickled; subsequent calls load the
    cache. A failed after-parse degrades to ``depadd=[]`` like the
    reference's ``helper`` (``:225-240``)."""
    from deepdfa_tpu.cpg.features import dep_add_lines

    if cache_path is not None:
        cache_path = Path(cache_path)
        if cache and cache_path.exists():
            with open(cache_path, "rb") as f:
                return pickle.load(f)

    out: dict[int, dict[str, list[int]]] = {}
    for row in records:
        fid = int(row["id"])
        if int(row.get("vul", 1)) != 1 or fid not in cpgs:
            continue
        removed = sorted(set(row.get("removed") or []))
        added = list(row.get("added") or [])
        depadd: list[int] = []
        if added and row.get("after"):
            try:
                depadd = dep_add_lines(cpgs[fid], parse(row["after"]), added)
            except Exception:  # noqa: BLE001 — label fallback: removed only
                depadd = []
        out[fid] = {"removed": removed, "depadd": depadd}

    if cache_path is not None:
        cache_path.parent.mkdir(parents=True, exist_ok=True)
        atomic_write_bytes(cache_path, pickle.dumps(out))
    return out
