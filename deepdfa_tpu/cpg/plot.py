"""CPG / CFG visualization as Graphviz DOT text.

The reference shipped a graphviz plotting path that was broken at import
(``DDFA/sastvd/helpers/joern.py:5`` — commented-out import, dead
``plot_graph_node_edge_df`` surface). This emits plain DOT text instead: no
graphviz binary or python binding required to *produce* the artifact, and
any ``dot``/online viewer renders it. Optional reaching-definitions overlay
annotates each node with its solver OUT set, which is the debugging view the
learned-DFA experiments actually need.
"""

from __future__ import annotations

from pathlib import Path

from deepdfa_tpu.cpg.schema import CPG, RDG_ETYPES, rdg
from deepdfa_tpu.resilience.journal import atomic_write_text

__all__ = ["to_dot", "write_dot"]

_ETYPE_STYLE = {
    "CFG": ("solid", "black"),
    "AST": ("dotted", "gray50"),
    "REACHING_DEF": ("dashed", "blue"),
    "CDG": ("dashed", "red"),
    "DDG": ("dashed", "forestgreen"),
    "REF": ("dotted", "purple"),
    "ARGUMENT": ("dotted", "gray70"),
}


def _esc(s: str) -> str:
    return str(s).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def to_dot(
    cpg: CPG,
    gtype: str = "all",
    rd_out: dict[int, set] | None = None,
    max_code_chars: int = 40,
) -> str:
    """Render the ``gtype`` subgraph (``rdg`` etype selection, same keys as
    the training materializer) as DOT. ``rd_out``: optional node → set of
    reaching definitions (e.g. from ``ReachingDefinitions(cpg).solve()[1]``)
    appended to each node label as ``RD:{var@line,...}``."""
    edges = rdg(cpg, gtype)  # validates gtype
    etypes = RDG_ETYPES[gtype]
    # only endpoints that exist in the node table: a malformed export row
    # must not make Graphviz auto-create bare nodes
    keep = ({s for s, _ in edges} | {d for _, d in edges}) & set(cpg.nodes)
    lines = [
        "digraph cpg {",
        '  node [shape=box, fontname="monospace", fontsize=9];',
        '  edge [fontsize=8];',
    ]
    for nid in sorted(keep):
        n = cpg.nodes[nid]  # keep ⊆ cpg.nodes by construction above
        code = n.code[:max_code_chars] + ("…" if len(n.code) > max_code_chars else "")
        label = f"{nid} {n.label}"
        if n.line is not None:
            label += f" L{n.line}"
        if code:
            label += f"\n{code}"
        if rd_out is not None and rd_out.get(nid):
            def _def_label(d) -> str:
                # VariableDefinition(var, node, ...) — line comes from the
                # defining node; fall back to repr for foreign set elements
                dn = cpg.nodes.get(getattr(d, "node", -1))
                if hasattr(d, "var"):
                    line = dn.line if dn is not None and dn.line is not None else "?"
                    return f"{d.var}@{line}"
                return str(d)

            defs = sorted(_def_label(d) for d in rd_out[nid])
            label += "\nRD:{" + ",".join(defs) + "}"
        lines.append(f'  n{nid} [label="{_esc(label)}"];')
    for s, d, e in cpg.edges:
        if e not in etypes or s not in keep or d not in keep:
            continue
        style, color = _ETYPE_STYLE.get(e, ("solid", "gray30"))
        lines.append(
            f'  n{s} -> n{d} [style={style}, color={color}, label="{_esc(e)}"];'
        )
    lines.append("}")
    return "\n".join(lines) + "\n"


def write_dot(cpg: CPG, path: str | Path, **kwargs) -> Path:
    path = Path(path)
    atomic_write_text(path, to_dot(cpg, **kwargs), encoding="utf-8")
    return path
