"""Abstract-dataflow feature extraction over CPGs.

Stage 1+2 of the reference's feature pipeline
(``DDFA/sastvd/scripts/abstract_dataflow_full.py``): for every *definition*
node (a CALL whose name is an assignment/inc-dec operator,
``abstract_dataflow_full.py:44-51``) collect four families of "subkeys"
describing the definition abstractly:

- ``datatype`` — the declared type of the assigned variable, resolved by
  recursing through access/cast operators to the underlying IDENTIFIER
  (``abstract_dataflow_full.py:67-125``), then normalised
  (``:240-250``: array extents dropped, leading ``const`` dropped,
  whitespace collapsed);
- ``literal`` / ``operator`` / ``api`` — the codes/names of LITERAL and CALL
  nodes in the definition's AST subtree (METHOD subtrees excluded,
  ``:127-167``); ``<operator>.X`` calls contribute ``X`` as an operator
  (``indirection`` excluded), every other call name is an ``api``.

Stage 2 groups subkeys per definition into a canonical JSON "hash"
(``:285-295``). Known deliberate deviation: the reference's operator regex
only matches the ``<operator>.`` spelling, so ``<operators>.``-spelled
operators (a Joern quirk) leak into the ``api`` family; we treat both
spellings as operators.

Line-level dependency labeling (``helpers/evaluate.py:194-218``): lines
data/control-dependent on patch-added lines, used to extend per-line
vulnerability labels beyond the removed lines.
"""

from __future__ import annotations

import json
import re
from typing import Iterable

import pandas as pd

from deepdfa_tpu.cpg.schema import CPG
from deepdfa_tpu.cpg.dataflow import ASSIGNMENT_OPS, INC_DEC_OPS

__all__ = [
    "DEF_OPS",
    "is_def",
    "clean_datatype",
    "definition_subkeys",
    "extract_features",
    "features_to_hashes",
    "line_dependencies",
    "dep_add_lines",
    "add_dependence_edges",
    "dataflow_node_features",
]

# Definition detection for *feature extraction*: the reference's
# all_assignment_types (abstract_dataflow_full.py:24-42) — the 13 assignment
# ops + 4 inc/dec ops (no incBy), in both operator spellings.
_DEF_BASE = tuple(op for op in ASSIGNMENT_OPS) + tuple(
    op for op in INC_DEC_OPS if not op.endswith("incBy")
)
DEF_OPS = frozenset(
    _DEF_BASE + tuple(op.replace("<operator>", "<operators>") for op in _DEF_BASE)
)

# Operators whose argument at the given order carries the underlying variable
# when resolving a datatype (abstract_dataflow_full.py:72-84).
_RECURSE_ARG_ORDER = {
    "indirectIndexAccess": 1,
    "indirectFieldAccess": 1,
    "indirection": 1,
    "fieldAccess": 1,
    "postIncrement": 1,
    "postDecrement": 1,
    "preIncrement": 1,
    "preDecrement": 1,
    "addressOf": 1,
    "cast": 2,
    "addition": 1,
}


def _op_name(name: str) -> str | None:
    """``<operator>.X``/``<operators>.X`` → ``X``; None for plain calls."""
    m = re.match(r"<operators?>\.(.*)", name)
    return m.group(1) if m else None


def is_def(cpg: CPG, nid: int) -> bool:
    node = cpg.nodes.get(nid)
    return node is not None and node.label == "CALL" and node.name in DEF_OPS


def clean_datatype(dt: str) -> str:
    """Normalise a type string (``abstract_dataflow_full.py:240-250``)."""
    dt = re.sub(r"\s*\[.*\]", "[]", dt)
    dt = re.sub(r"^const ", "", dt)
    return re.sub(r"\s+", " ", dt).strip()


def _recurse_datatype(cpg: CPG, v: int) -> tuple[int, str]:
    attr = cpg.nodes[v]
    if attr.label == "IDENTIFIER":
        return v, attr.type_full_name
    if attr.label == "CALL":
        op = _op_name(attr.name)
        if op in _RECURSE_ARG_ORDER:
            args = cpg.arguments(v)
            arg = args.get(_RECURSE_ARG_ORDER[op])
            if arg is None:
                raise LookupError(f"no arg {_RECURSE_ARG_ORDER[op]} on {v}")
            arg_attr = cpg.nodes[arg]
            if arg_attr.label == "IDENTIFIER":
                return arg, arg_attr.type_full_name
            if arg_attr.label == "CALL":
                return _recurse_datatype(cpg, arg)
            raise LookupError(f"unhandled arg {arg} ({arg_attr.label})")
    raise LookupError(f"unhandled node {v} ({attr.label} {attr.name})")


def _raw_datatype(cpg: CPG, decl: int) -> tuple[int, str]:
    """(node, raw type) of the variable defined at ``decl``
    (``abstract_dataflow_full.py:109-125``)."""
    attr = cpg.nodes[decl]
    if attr.label == "LOCAL":
        return decl, attr.type_full_name
    cast_ops = DEF_OPS | {"<operator>.cast", "<operators>.cast"}
    if attr.label == "CALL" and attr.name in cast_ops:
        args = cpg.arguments(decl)
        if 1 not in args:
            raise LookupError(f"no first arg on {decl}")
        return _recurse_datatype(cpg, args[1])
    raise LookupError(f"unhandled decl {decl} ({attr.label})")


def definition_subkeys(cpg: CPG, nid: int, raise_all: bool = False) -> list[tuple[str, int, str]]:
    """Subkey fields ``(subkey, subkey_node, text)`` for one definition node
    (``abstract_dataflow_full.py:127-167``)."""
    fields: list[tuple[str, int, str]] = []
    try:
        try:
            child, dt = _raw_datatype(cpg, nid)
            fields.append(("datatype", child, clean_datatype(dt)))
        except LookupError:
            if raise_all:
                raise
        for n in cpg.ast_descendants(nid, skip_labels=frozenset({"METHOD"})):
            attr = cpg.nodes.get(n)
            if attr is None:
                continue
            if attr.label == "LITERAL":
                fields.append(("literal", n, attr.code))
            elif attr.label == "CALL":
                op = _op_name(attr.name)
                if op is not None:
                    if op != "indirection":
                        fields.append(("operator", n, op))
                else:
                    fields.append(("api", n, attr.name))
    except Exception:
        if raise_all:
            raise
    return fields


def extract_features(
    cpg: CPG, graph_id: int, raise_all: bool = False
) -> pd.DataFrame:
    """Stage 1 for one graph: rows
    ``(graph_id, node_id, subkey, subkey_node_id, subkey_text)``."""
    rows = []
    for nid in cpg.nodes:
        if not is_def(cpg, nid):
            continue
        for subkey, sk_node, text in definition_subkeys(cpg, nid, raise_all=raise_all):
            rows.append(
                dict(
                    graph_id=graph_id,
                    node_id=nid,
                    subkey=subkey,
                    subkey_node_id=sk_node,
                    subkey_text=text,
                )
            )
    return pd.DataFrame(
        rows, columns=["graph_id", "node_id", "subkey", "subkey_node_id", "subkey_text"]
    )


def features_to_hashes(feature_df: pd.DataFrame, subkeys: Iterable[str]) -> pd.DataFrame:
    """Stage 2: group per definition into a canonical JSON hash
    ``{"api": [...], "datatype": [...], ...}`` with sorted value lists
    (``abstract_dataflow_full.py:285-334``)."""
    subkeys = sorted(subkeys)
    if feature_df.empty:
        return pd.DataFrame(columns=["graph_id", "node_id", "hash"])

    def to_hash(group: pd.DataFrame) -> str:
        return json.dumps(
            {
                sk: sorted(group[group["subkey"] == sk]["subkey_text"].astype(str))
                for sk in subkeys
            }
        )

    out = (
        feature_df.groupby(["graph_id", "node_id"])[feature_df.columns]
        .apply(to_hash, include_groups=False)
        .rename("hash")
        .reset_index()
    )
    return out.sort_values(["graph_id", "node_id"]).reset_index(drop=True)


def dataflow_node_features(cpg: CPG) -> dict[str, dict[int, int]]:
    """Per-CFG-node raw values for the static-analysis feature families
    (``config.DFA_FAMILIES``), solved with the native backend (which falls
    back to the bit-vector solver on toolchain-less hosts):

    - ``live_out`` — |live_out(n)| clipped to ``DFA_LIVE_OUT_CLIP``;
    - ``uninit`` — 1 iff ``n`` reads a possibly-uninitialized local;
    - ``taint`` — 0 untouched / 1 uses a tainted variable / 2 introduces
      taint (source call, tainted assignment, parameter entry).

    Nodes outside the CFG are absent; carriers default them to 0.
    """
    from deepdfa_tpu.config import DFA_LIVE_OUT_CLIP
    from deepdfa_tpu.cpg import analyses

    live = analyses.solve_native(analyses.liveness(cpg))
    live_out = {n: min(len(s), DFA_LIVE_OUT_CLIP) for n, s in live.out_facts.items()}
    uninit_sol = analyses.solve_native(analyses.uninitialized(cpg))
    flagged = analyses.uninitialized_uses(cpg, uninit_sol)
    uninit = {n: int(n in flagged) for n in uninit_sol.in_facts}
    taint = analyses.taint_node_codes(cpg, solver=analyses.solve_native)
    return {"live_out": live_out, "uninit": uninit, "taint": taint}


# ---------------------------------------------------------------------------
# line-level dependency labeling


def line_dependencies(cpg: CPG) -> dict[int, set[int]]:
    """Undirected line-level data+control dependency map.

    PDG edges (REACHING_DEF as data, CDG as control) projected onto line
    numbers, symmetrised, self-loops dropped — the construction behind the
    reference's per-line ``data``/``control`` context
    (``helpers/evaluate.py:124-171``), merged into one set per line since the
    labeler unions both anyway (``:209-211``)."""
    line_of = {i: n.line for i, n in cpg.nodes.items() if n.line is not None}
    deps: dict[int, set[int]] = {}
    for s, d, e in cpg.edges:
        if e not in ("REACHING_DEF", "CDG"):
            continue
        ls, ld = line_of.get(s), line_of.get(d)
        if ls is None or ld is None or ls == ld:
            continue
        deps.setdefault(ls, set()).add(ld)
        deps.setdefault(ld, set()).add(ls)
    return deps


def dep_add_lines(
    before_cpg: CPG, after_cpg: CPG, added_lines: Iterable[int]
) -> list[int]:
    """Lines of the *before* function that are data/control-dependent on
    patch-added lines (computed in the *after* graph)
    (``helpers/evaluate.py:194-218``)."""
    added = set(added_lines)
    deps = line_dependencies(after_cpg)
    dependent: set[int] = set()
    for line in added:
        dependent |= deps.get(line, set())
    before_lines = {n.line for n in before_cpg.nodes.values() if n.line is not None}
    return sorted(dependent & before_lines)


def add_dependence_edges(cpg: CPG) -> CPG:
    """Augment a CPG with REACHING_DEF (data) and CDG (control) edges.

    The reference gets both from Joern's engine (``run.ossdataflow``,
    ``get_func_graph.sc:31``); for natively-extracted CPGs we derive them:

    - REACHING_DEF: for each definition ``d`` of variable ``v`` reaching node
      ``n`` (our worklist solver's IN set), an edge ``d → n`` iff ``n``'s
      statement mentions ``v`` (an IDENTIFIER AST-descendant named ``v``, or
      ``n`` itself being that identifier's statement);
    - CDG: control dependence via post-dominance — CFG node ``m`` is
      control-dependent on branch node ``b`` iff ``b`` has a successor path
      to exit avoiding ``m``'s post-dominators but ``m`` post-dominates some
      successor of ``b`` (standard Ferrante-Ottenstein-Warren construction
      on the reverse CFG).

    Returns a new CPG sharing node objects; existing edges are preserved.
    """
    from deepdfa_tpu.cpg.dataflow import ReachingDefinitions, solve_bitvec, solve_native

    rd = ReachingDefinitions(cpg)
    # Throughput path: C++ worklist (falls back to the NumPy bit-matrix
    # solver if the native lib can't build); both are parity-tested against
    # the Python set solver. They return def-node ids — map back to
    # VariableDefinitions for the var-name matching below.
    try:
        in_ids, out_ids = solve_native(rd)
    except Exception:  # noqa: BLE001 — toolchain-less hosts
        in_ids, out_ids = solve_bitvec(rd)
    def_by_node = {d.node: d for defs in rd.gen.values() for d in defs}
    in_sets = {n: {def_by_node[i] for i in s} for n, s in in_ids.items()}
    out_sets = {n: {def_by_node[i] for i in s} for n, s in out_ids.items()}
    new_edges: list[tuple[int, int, str]] = list(cpg.edges)

    # --- data dependence. Definitions are matched *textually* (the solver's
    # var is the lvalue's source text, dataflow.py:109-123), so uses must
    # include compound expressions too: "*p", "a[i]", "s->f" are CALL nodes,
    # not bare IDENTIFIERs.
    def mentioned_vars(n: int) -> set[str]:
        out = set()
        for d in [n, *cpg.ast_descendants(n)]:
            nd = cpg.nodes.get(d)
            if nd is not None and nd.label in ("IDENTIFIER", "CALL"):
                out.add(nd.code)
        return out

    for n, defs in in_sets.items():
        uses = mentioned_vars(n)
        if not uses:
            continue
        for d in defs:
            if d.var in uses and d.node != n:
                new_edges.append((d.node, n, "REACHING_DEF"))

    # --- control dependence (post-dominator frontier on the CFG)
    cfg_nodes = sorted(cpg.edge_nodes("CFG"))
    if cfg_nodes:
        succs = {n: list(cpg.successors(n, "CFG")) for n in cfg_nodes}
        preds = {n: list(cpg.predecessors(n, "CFG")) for n in cfg_nodes}
        exits = [n for n in cfg_nodes if not succs[n]]
        # virtual exit -1 joins all sinks so post-dominance is well-defined
        VEXIT = -1
        for n in exits:
            succs[n] = [VEXIT]
        preds[VEXIT] = list(exits)
        succs[VEXIT] = []
        allnodes = cfg_nodes + [VEXIT]
        # iterative post-dominator sets (reverse-CFG dominators)
        full = set(allnodes)
        pdom = {n: ({n} if n == VEXIT else set(full)) for n in allnodes}
        changed = True
        while changed:
            changed = False
            for n in allnodes:
                if n == VEXIT:
                    continue
                ss = succs[n]
                inter = set.intersection(*(pdom[s] for s in ss)) if ss else set()
                new = {n} | inter
                if new != pdom[n]:
                    pdom[n] = new
                    changed = True
        # Ferrante-Ottenstein-Warren: for each branch edge (b, s), every node
        # on the post-dominator chain of s up to (but excluding) b's strict
        # post-dominators is control-dependent on b.
        for b in cfg_nodes:
            if len(succs[b]) < 2:
                continue
            strict_pdom_b = pdom[b] - {b}
            for s in succs[b]:
                if s == VEXIT:
                    continue
                for m in pdom[s] - strict_pdom_b:
                    if m != VEXIT:
                        new_edges.append((b, m, "CDG"))

    seen = set()
    deduped = []
    for e in new_edges:
        if e not in seen:
            seen.add(e)
            deduped.append(e)
    out = CPG(list(cpg.nodes.values()), deduped)
    # cache the fixpoint so downstream label materialisation
    # (graph_from_cpg(dataflow_labels=True)) doesn't re-solve the same CPG
    out.rd_solution = (in_sets, out_sets)
    return out
