"""Native C frontend: C source → Joern-compatible CPG, no JVM.

The reference's only CPG producer is Joern (``scripts/install_joern.sh``,
pinned v1.1.107, invoked per function via ``get_func_graph.sc``). That stays
supported as an ingestion path (:mod:`deepdfa_tpu.cpg.joern`), but extraction
throughput there is JVM-bound and needs an external install; this module
builds the same graph shape natively with **pycparser**, so preprocessing,
tests and benchmarks are hermetic.

Output contract (what downstream consumes — the reaching-definitions solvers
and the abstract-dataflow extractor):

- node labels: METHOD, METHOD_PARAMETER_IN, METHOD_RETURN, BLOCK, LOCAL,
  CALL, IDENTIFIER, LITERAL, CONTROL_STRUCTURE, RETURN, JUMP_TARGET;
- operator calls named in Joern's ``<operator>.*`` vocabulary (assignment
  family, inc/dec, arithmetic, comparisons, indexAccess, fieldAccess /
  indirectFieldAccess, indirection, addressOf, cast, conditional);
- ``AST`` edges parent→child, ``ARGUMENT`` edges call→operand (``order``
  1-based), ``CFG`` edges in evaluation order — **branch-sensitive**: the
  ternary operator and short-circuiting ``&&``/``||`` fork the CFG exactly
  like ``if`` does, so path-sensitive analyses (reaching definitions) see
  both arms;
- IDENTIFIER/LOCAL/METHOD_PARAMETER_IN nodes carry ``typeFullName`` resolved
  from the local scope (declarations seen so far), arrays rendered
  ``T[n]``, pointers ``T *``.

Deviation from Joern, by design: the CFG chains only *call-level* nodes
(operator/function calls, plus METHOD / RETURN / JUMP_TARGET /
METHOD_RETURN) rather than every leaf expression. Non-call nodes neither gen
nor kill definitions, and branching constructs fork the CFG as above, so
reaching definitions are unaffected while graphs shrink ~2× — free TPU
throughput downstream.

C is parsed after a lightweight in-process preprocess: comments and
``#``-directives are stripped; unknown typedef'd types are recovered by (a) a
pre-pass typedefing statement-initial ``X *y`` declarations (which pycparser
would otherwise mis-parse as multiplication — C resolves the ambiguity as a
declaration), and (b) iteratively inserting ``typedef int X;`` on parse
errors (pycparser needs closed types, not real headers).

CFG lowering protocol: every expression/statement lowers to a *fragment*
``(entries, exits)`` — the CFG nodes control enters through / falls out of.
Transparent constructs (leaves, empty statements) have empty fragments;
sequencing, branching and loops wire fragments together.
"""

from __future__ import annotations

import re

import pycparser
from pycparser import c_ast
from pycparser.c_parser import ParseError

from deepdfa_tpu.cpg.schema import CPG, Node

__all__ = ["parse_function", "parse_source", "strip_comments", "FrontendError"]


class FrontendError(ValueError):
    pass


BINARY_OPS = {
    "+": "addition",
    "-": "subtraction",
    "*": "multiplication",
    "/": "division",
    "%": "modulo",
    "<": "lessThan",
    ">": "greaterThan",
    "<=": "lessEqualsThan",
    ">=": "greaterEqualsThan",
    "==": "equals",
    "!=": "notEquals",
    "&&": "logicalAnd",
    "||": "logicalOr",
    "&": "and",
    "|": "or",
    "^": "xor",
    "<<": "shiftLeft",
    ">>": "arithmeticShiftRight",
}
ASSIGN_OPS = {
    "=": "assignment",
    "+=": "assignmentPlus",
    "-=": "assignmentMinus",
    "*=": "assignmentMultiplication",
    "/=": "assignmentDivision",
    "%=": "assignmentModulo",
    "&=": "assignmentAnd",
    "|=": "assignmentOr",
    "^=": "assignmentXor",
    "<<=": "assignmentShiftLeft",
    ">>=": "assignmentArithmeticShiftRight",
}
UNARY_OPS = {
    "++": "preIncrement",
    "--": "preDecrement",
    "p++": "postIncrement",
    "p--": "postDecrement",
    "*": "indirection",
    "&": "addressOf",
    "-": "minus",
    "+": "plus",
    "!": "logicalNot",
    "~": "not",
    "sizeof": "sizeOf",
}


def strip_comments(code: str) -> str:
    """Remove // and /* */ comments, preserving line numbers (same job as the
    reference's ``remove_comments``, ``helpers/datasets.py:19-33``)."""

    def repl(m):
        s = m.group(0)
        if s.startswith("/"):
            return "\n" * s.count("\n") if s.startswith("/*") else ""
        return s

    pattern = r"//[^\n]*|/\*.*?\*/|\"(?:\\.|[^\"\\])*\"|'(?:\\.|[^'\\])*'"
    return re.sub(pattern, repl, code, flags=re.DOTALL)


def _blank_span(text: str) -> str:
    """Replace a span with spaces, preserving newlines (and therefore every
    line/column the parser will report)."""
    return "".join(ch if ch == "\n" else " " for ch in text)


def _match_paren(code: str, i: int) -> int | None:
    """Index just past the ``)`` matching the ``(`` at ``i`` — skipping
    parens inside string/char literals (extended asm templates contain
    them, e.g. ``asm("save (" ::: "memory")``)."""
    depth = 0
    k = i
    while k < len(code):
        ch = code[k]
        if ch in "\"'":
            quote = ch
            k += 1
            while k < len(code) and code[k] != quote:
                k += 2 if code[k] == "\\" else 1
        elif ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                return k + 1
        k += 1
    return None


def _scrub_kw_parens(code: str, keyword_re: re.Pattern, repl: str) -> str:
    """Blank every ``keyword (...balanced...)`` construct, substituting
    ``repl`` at the keyword position (length-padded)."""
    out = []
    pos = 0
    while True:
        m = keyword_re.search(code, pos)
        if not m:
            out.append(code[pos:])
            return "".join(out)
        i = code.find("(", m.end() - 1)
        j = _match_paren(code, i) if i >= 0 else None
        if i < 0 or j is None:  # unbalanced — leave for the parser to report
            out.append(code[pos:m.end()])
            pos = m.end()
            continue
        span = code[m.start():j]
        blanked = _blank_span(span)
        out.append(code[pos:m.start()])
        out.append(repl + blanked[len(repl):] if len(repl) <= len(blanked) else repl)
        pos = j


_ATTR_RE = re.compile(r"\b__attribute__\s*(?=\()")
_ASM_RE = re.compile(r"\b(?:__asm__|__asm|asm)\b\s*(?:__volatile__|volatile)?\s*(?=\()")
_TYPEOF_RE = re.compile(r"\b(?:__typeof__|__typeof|typeof)\s*(?=\()")
# GNU spelling → standard spelling, length-padded so columns survive
_GNU_TOKEN_MAP = [
    (re.compile(r"\b__restrict__\b"), "restrict"),
    (re.compile(r"\b__restrict\b"), "restrict"),
    (re.compile(r"\b__inline__\b"), "inline"),
    (re.compile(r"\b__inline\b"), "inline"),
    (re.compile(r"\b__volatile__\b"), "volatile"),
    (re.compile(r"\b__signed__\b"), "signed"),
    (re.compile(r"\b__const\b"), "const"),
    (re.compile(r"\b__extension__\b"), ""),
]
_CASE_RANGE_RE = re.compile(r"(\bcase\b[^:\n]*?)\.\.\.[^:\n]*(:)")
_GENERIC_RE = re.compile(r"\b_Generic\s*(?=\()")
# `goto *expr;` — dynamic target, statically unresolvable even for Joern;
# degraded to an empty statement (the labels themselves parse fine)
_COMPUTED_GOTO_RE = re.compile(r"\bgoto\s*\*[^;\n]*;")
# address-of-label `&&lbl` in unary position ONLY: immediately after = ( ,
# { ? : (brace-initialized label tables, ternary arms) or `return` —
# anywhere else `&&` is the binary operator and must survive
_ADDR_LABEL_RE = re.compile(r"([=(,{?:]\s*|\breturn\s+)&&\s*\w+")
# digraphs are alternative spellings of { } [ ] (C11 6.4.6); replace outside
# string/char literals, column-padded
_DIGRAPH_OR_LITERAL_RE = re.compile(
    r"\"(?:\\.|[^\"\\])*\"|'(?:\\.|[^'\\])*'|<%|%>|<:|:>"
)
_DIGRAPH_MAP = {"<%": "{ ", "%>": "} ", "<:": "[ ", ":>": "] "}
# an ALL-CAPS call alone on a line with the block opener on the next line —
# the `LIST_FOREACH(x, list)\n{` shape of statement-like macros; appending a
# `;` turns it into a call statement followed by a plain block, keeping the
# block's statements in the CFG
_MACRO_BLOCK_RE = re.compile(
    r"^([ \t]*[A-Z][A-Z0-9_]*\s*\([^;{}\n]*\))(?=[ \t]*(?:\n\s*)?\{)",
    re.MULTILINE,
)


def _scrub_gnu_extensions(code: str) -> str:
    """Cheap, line/column-preserving scrubs for the constructs a header-less
    Big-Vul-style function actually contains but pycparser cannot eat:
    ``__attribute__((...))``, (extended) asm, ``typeof(x)`` (degraded to
    ``int`` — extraction cares about the CFG/def-use shape, not the inferred
    type), GNU keyword spellings, ``case a ... b:`` ranges, and statement
    macros that open a block. Everything is blanked with spaces, never
    removed, so parser positions keep pointing at the original source."""
    code = _scrub_kw_parens(code, _ATTR_RE, "")
    code = _scrub_kw_parens(code, _ASM_RE, "")
    code = _scrub_kw_parens(code, _TYPEOF_RE, "int")
    # `_Generic(...)` selections degrade to 0 — extraction cares about the
    # CFG/def-use shape, not the type-dispatched value
    code = _scrub_kw_parens(code, _GENERIC_RE, "0")
    code = _DIGRAPH_OR_LITERAL_RE.sub(
        lambda m: _DIGRAPH_MAP.get(m.group(0), m.group(0)), code
    )
    code = _COMPUTED_GOTO_RE.sub(
        lambda m: _blank_span(m.group(0)[:-1]) + ";", code
    )
    code = _ADDR_LABEL_RE.sub(
        lambda m: m.group(1) + "0" + " " * (len(m.group(0)) - len(m.group(1)) - 1),
        code,
    )
    for pat, repl in _GNU_TOKEN_MAP:
        code = pat.sub(lambda m, r=repl: r + " " * (len(m.group(0)) - len(r)), code)
    code = _CASE_RANGE_RE.sub(
        lambda m: m.group(1) + " " * (len(m.group(0)) - len(m.group(1)) - 1) + m.group(2),
        code,
    )
    code = _MACRO_BLOCK_RE.sub(lambda m: m.group(1) + ";", code)
    return code


def _preprocess(code: str) -> str:
    code = strip_comments(code)
    lines = []
    for ln in code.split("\n"):
        if ln.lstrip().startswith("#"):
            lines.append("")  # keep line numbering
        else:
            lines.append(ln)
    return _scrub_gnu_extensions("\n".join(lines))


_PARSE_ERR_RE = re.compile(r":(\d+):(\d+): before: (\S+)")

_C_KEYWORDS = frozenset(
    "auto break case char const continue default do double else enum extern "
    "float for goto if inline int long register restrict return short signed "
    "sizeof static struct switch typedef union unsigned void volatile while".split()
)
_BUILTIN_TYPE_WORDS = _C_KEYWORDS | {"ANY"}
# identifier followed by (pointer stars and) another identifier then a
# declarator-ish delimiter — the `X y,` / `X *y)` shape of a typedef'd type
_TYPEISH_RE = re.compile(
    r"\b([A-Za-z_]\w*)(?:\s+\*{0,3}\s*|\s*\*{1,3}\s*)[A-Za-z_]\w*\s*[,)=;[]"
)
# statement-initial `X *y = ...` / `X *y;`: C resolves this ambiguity as a
# declaration, so X must be a type — but pycparser happily parses it as
# multiplication when X is an unknown typedef name, silently corrupting the
# graph. Typedef these proactively before the first parse.
_DECL_PTR_RE = re.compile(
    r"(?:^|[;{}])\s*([A-Za-z_]\w*)\s*\*+\s*[A-Za-z_]\w*\s*[=;,[]", re.MULTILINE
)


def _unknown_type_candidate(source: str, err: ParseError) -> str | None:
    """pycparser reports the token *after* an unknown type name
    (``size_t n`` errors at ``n``); recover the identifier immediately
    preceding the error position."""
    m = _PARSE_ERR_RE.search(str(err))
    if not m:
        return None
    line_no, col, _tok = int(m.group(1)), int(m.group(2)), m.group(3)
    lines = source.split("\n")
    if not (1 <= line_no <= len(lines)):
        return None
    before = lines[line_no - 1][: col - 1]
    im = re.search(r"([A-Za-z_]\w*)\s*\**\s*$", before)
    if not im:
        return None
    cand = im.group(1)
    if cand in _C_KEYWORDS:
        return None
    return cand


def _parse_with_recovery(code: str, max_retries: int = 25):
    """Parse; on unknown-type errors, prepend ``typedef int X;`` and retry
    (bounded). Recovers typedef'd types without real headers. Returns
    (ast, number of typedef lines prepended)."""
    typedefs: list[str] = [
        t
        for t in dict.fromkeys(_DECL_PTR_RE.findall(code))
        if t not in _BUILTIN_TYPE_WORDS
    ]
    used_bulk = False
    last_err = None
    for _ in range(max_retries):
        prefix = "".join(f"typedef int {t};\n" for t in typedefs)
        source = prefix + code
        try:
            return pycparser.CParser().parse(source, "<func>"), len(typedefs)
        except ParseError as e:
            last_err = e
            cand = _unknown_type_candidate(source, e)
            if cand is not None and cand not in typedefs:
                typedefs.append(cand)
                continue
            if not used_bulk:
                # positionless errors ("Invalid declaration"): typedef every
                # type-looking identifier in one shot and retry once
                used_bulk = True
                bulk = [
                    t
                    for t in dict.fromkeys(_TYPEISH_RE.findall(code))
                    if t not in _BUILTIN_TYPE_WORDS and t not in typedefs
                ]
                if bulk:
                    typedefs.extend(bulk)
                    continue
            break
    raise FrontendError(f"cannot parse C source: {last_err}")


def _render_type(node) -> str:
    """Render a pycparser type node to a Joern-ish type string."""
    if isinstance(node, c_ast.TypeDecl):
        quals = " ".join(q for q in node.quals if q != "const")
        base = _render_type(node.type)
        return (quals + " " + base).strip()
    if isinstance(node, c_ast.IdentifierType):
        return " ".join(node.names)
    if isinstance(node, c_ast.PtrDecl):
        return _render_type(node.type) + " *"
    if isinstance(node, c_ast.ArrayDecl):
        dim = ""
        if node.dim is not None and isinstance(node.dim, c_ast.Constant):
            dim = node.dim.value
        return f"{_render_type(node.type)}[{dim}]"
    if isinstance(node, c_ast.Struct):
        return f"struct {node.name or ''}".strip()
    if isinstance(node, c_ast.Union):
        return f"union {node.name or ''}".strip()
    if isinstance(node, c_ast.Enum):
        return f"enum {node.name or ''}".strip()
    if isinstance(node, c_ast.FuncDecl):
        return _render_type(node.type)
    return "ANY"


def _code_of(node) -> str:
    """Best-effort source rendering of an expression subtree."""
    return _CodeGen().visit(node)


class _CodeGen:
    def visit(self, n) -> str:
        if n is None:
            return ""
        meth = getattr(self, "v_" + type(n).__name__, None)
        return meth(n) if meth else "..."

    def v_Constant(self, n):
        return n.value

    def v_ID(self, n):
        return n.name

    def v_ArrayRef(self, n):
        return f"{self.visit(n.name)}[{self.visit(n.subscript)}]"

    def v_StructRef(self, n):
        return f"{self.visit(n.name)}{n.type}{self.visit(n.field)}"

    def v_UnaryOp(self, n):
        if n.op in ("p++", "p--"):
            return f"{self.visit(n.expr)}{n.op[1:]}"
        if n.op == "sizeof":
            return f"sizeof({self.visit(n.expr)})"
        return f"{n.op}{self.visit(n.expr)}"

    def v_BinaryOp(self, n):
        return f"{self.visit(n.left)} {n.op} {self.visit(n.right)}"

    def v_Assignment(self, n):
        return f"{self.visit(n.lvalue)} {n.op} {self.visit(n.rvalue)}"

    def v_FuncCall(self, n):
        args = ", ".join(self.visit(a) for a in (n.args.exprs if n.args else []))
        return f"{self.visit(n.name)}({args})"

    def v_Cast(self, n):
        return f"({_render_type(n.to_type.type)}){self.visit(n.expr)}"

    def v_TernaryOp(self, n):
        return f"{self.visit(n.cond)} ? {self.visit(n.iftrue)} : {self.visit(n.iffalse)}"

    def v_ExprList(self, n):
        return ", ".join(self.visit(e) for e in n.exprs)

    def v_Typename(self, n):
        return _render_type(n.type)

    def v_Decl(self, n):
        return n.name or ""


# A CFG fragment: nodes control enters through, nodes control falls out of.
Frag = tuple[list[int], list[int]]
EMPTY: Frag = ([], [])


class _Builder:
    """Walk one FunctionDef, emit nodes/edges, build the call-level CFG."""

    def __init__(self, line_offset: int = 0, next_id: int = 1000100):
        self.nodes: list[Node] = []
        self.edges: list[tuple[int, int, str]] = []
        self._next = next_id
        self.scope: list[dict[str, str]] = [{}]
        self.line_offset = line_offset
        self.method_return: int | None = None
        self._breaks: list[list[int]] = []
        self._continues: list[list[int]] = []
        self._labels: dict[str, int] = {}
        self._gotos: list[tuple[int, str]] = []

    # -- infra -----------------------------------------------------------
    def nid(self) -> int:
        self._next += 1
        return self._next

    def add_node(self, label, name="", code="", line=None, order=0, type_full_name="") -> int:
        i = self.nid()
        if line is not None:
            line = line - self.line_offset
        self.nodes.append(
            Node(i, label, name=name, code=code, line=line, order=order,
                 type_full_name=type_full_name)
        )
        return i

    def ast_edge(self, parent: int, child: int):
        self.edges.append((parent, child, "AST"))

    def arg_edge(self, call: int, arg: int):
        self.edges.append((call, arg, "ARGUMENT"))

    def cfg_edge(self, a: int, b: int):
        self.edges.append((a, b, "CFG"))

    def wire(self, frm: list[int], to: list[int]) -> None:
        for a in frm:
            for b in to:
                self.cfg_edge(a, b)

    def seq(self, *frags: Frag) -> Frag:
        """Sequence fragments, skipping transparent ones."""
        entries: list[int] = []
        exits: list[int] = []
        for e, x in frags:
            if not e and not x:
                continue
            if not entries:
                entries = e
            else:
                self.wire(exits, e)
            exits = x
        return entries, exits

    def lookup(self, name: str) -> str:
        for frame in reversed(self.scope):
            if name in frame:
                return frame[name]
        return "ANY"

    def line(self, n) -> int | None:
        try:
            return n.coord.line if n.coord else None
        except AttributeError:
            return None

    # -- expressions -----------------------------------------------------
    def expr(self, n, order: int = 1) -> tuple[int, Frag]:
        """Lower an expression; returns (root AST node id, CFG fragment)."""
        line = self.line(n)
        if isinstance(n, c_ast.Constant):
            tfn = {"int": "int", "float": "double", "double": "double",
                   "char": "char", "string": "char *"}.get(n.type, n.type)
            i = self.add_node("LITERAL", code=n.value, line=line, order=order,
                              type_full_name=tfn)
            return i, EMPTY
        if isinstance(n, c_ast.ID):
            i = self.add_node("IDENTIFIER", name=n.name, code=n.name, line=line,
                              order=order, type_full_name=self.lookup(n.name))
            return i, EMPTY
        if isinstance(n, c_ast.Assignment):
            op = ASSIGN_OPS[n.op]
            return self.call_node(f"<operator>.{op}", [n.lvalue, n.rvalue], n, order)
        if isinstance(n, c_ast.BinaryOp):
            if n.op in ("&&", "||"):
                return self.shortcircuit_node(n, order)
            op = BINARY_OPS.get(n.op, n.op)
            return self.call_node(f"<operator>.{op}", [n.left, n.right], n, order)
        if isinstance(n, c_ast.UnaryOp):
            op = UNARY_OPS.get(n.op, n.op)
            return self.call_node(f"<operator>.{op}", [n.expr], n, order)
        if isinstance(n, c_ast.ArrayRef):
            return self.call_node("<operator>.indexAccess", [n.name, n.subscript], n, order)
        if isinstance(n, c_ast.StructRef):
            op = "fieldAccess" if n.type == "." else "indirectFieldAccess"
            return self.call_node(f"<operator>.{op}", [n.name, n.field], n, order)
        if isinstance(n, c_ast.FuncCall):
            name = _code_of(n.name)
            args = list(n.args.exprs) if n.args else []
            return self.call_node(name, args, n, order)
        if isinstance(n, c_ast.Cast):
            # Joern: order 1 = type ref, order 2 = expression.
            call = self.add_node("CALL", name="<operator>.cast", code=_code_of(n),
                                 line=line, order=order)
            tref = self.add_node("TYPE_REF", code=_render_type(n.to_type.type),
                                 line=line, order=1,
                                 type_full_name=_render_type(n.to_type.type))
            self.ast_edge(call, tref)
            self.arg_edge(call, tref)
            sub, frag = self.expr(n.expr, order=2)
            self.ast_edge(call, sub)
            self.arg_edge(call, sub)
            frag = self.seq(frag, ([call], [call]))
            return call, frag
        if isinstance(n, c_ast.TernaryOp):
            return self.ternary_node(n, order)
        if isinstance(n, c_ast.ExprList):
            root = self.add_node("BLOCK", code=_code_of(n), line=line, order=order)
            frags = []
            for k, e in enumerate(n.exprs, 1):
                sub, fr = self.expr(e, order=k)
                self.ast_edge(root, sub)
                frags.append(fr)
            return root, self.seq(*frags)
        if isinstance(n, c_ast.Typename):
            t = _render_type(n.type)
            i = self.add_node("TYPE_REF", code=t, line=line, order=order, type_full_name=t)
            return i, EMPTY
        # fallback: opaque node, keeps graph well-formed
        i = self.add_node("UNKNOWN", code=_code_of(n), line=line, order=order)
        return i, EMPTY

    def call_node(self, name: str, operands: list, src, order: int) -> tuple[int, Frag]:
        """Strict-evaluation call: operand fragments in order, then the call."""
        line = self.line(src)
        call = self.add_node("CALL", name=name, code=_code_of(src), line=line, order=order)
        frags: list[Frag] = []
        for k, opnd in enumerate(operands, 1):
            sub, fr = self.expr(opnd, order=k)
            self.ast_edge(call, sub)
            self.arg_edge(call, sub)
            frags.append(fr)
        return call, self.seq(*frags, ([call], [call]))

    def shortcircuit_node(self, n: c_ast.BinaryOp, order: int) -> tuple[int, Frag]:
        """``a && b`` / ``a || b``: the right operand may be skipped, so the
        CFG forks after the left operand — both the right-operand path and the
        skip path reach the operator node."""
        line = self.line(n)
        op = BINARY_OPS[n.op]
        call = self.add_node("CALL", name=f"<operator>.{op}", code=_code_of(n),
                             line=line, order=order)
        lroot, lfrag = self.expr(n.left, order=1)
        self.ast_edge(call, lroot)
        self.arg_edge(call, lroot)
        rroot, rfrag = self.expr(n.right, order=2)
        self.ast_edge(call, rroot)
        self.arg_edge(call, rroot)
        if not rfrag[0]:
            # right side has no CFG nodes: degenerates to a plain chain
            return call, self.seq(lfrag, ([call], [call]))
        if lfrag[0]:
            self.wire(lfrag[1], rfrag[0])  # evaluate right
            self.wire(lfrag[1], [call])    # short-circuit skip
            self.wire(rfrag[1], [call])
            return call, (lfrag[0], [call])
        # left transparent: entry is both the right path and the call
        self.wire(rfrag[1], [call])
        return call, (rfrag[0] + [call], [call])

    def ternary_node(self, n: c_ast.TernaryOp, order: int) -> tuple[int, Frag]:
        """``c ? a : b`` forks like an if/else; both arms reach the operator."""
        line = self.line(n)
        call = self.add_node("CALL", name="<operator>.conditional", code=_code_of(n),
                             line=line, order=order)
        croot, cfrag = self.expr(n.cond, order=1)
        self.ast_edge(call, croot)
        self.arg_edge(call, croot)
        troot, tfrag = self.expr(n.iftrue, order=2)
        self.ast_edge(call, troot)
        self.arg_edge(call, troot)
        froot, ffrag = self.expr(n.iffalse, order=3)
        self.ast_edge(call, froot)
        self.arg_edge(call, froot)

        arm_entries: list[int] = []
        for e, x in (tfrag, ffrag):
            if e:
                arm_entries.extend(e)
                self.wire(x, [call])
            else:
                arm_entries.append(call)  # transparent arm falls straight through
        arm_entries = list(dict.fromkeys(arm_entries))
        if cfrag[0]:
            self.wire(cfrag[1], arm_entries)
            return call, (cfrag[0], [call])
        return call, (arm_entries, [call])

    def cond_frag(self, croot: int, cfrag: Frag) -> Frag:
        """Branch conditions are ALWAYS CFG-evaluated. A bare identifier /
        literal / member condition (``if (ptr)``, ``while (n)``,
        ``switch (op)``) lowers to an expression with no CALL inside, so its
        fragment is empty — without this, the construct would have no branch
        node: no path-sensitivity for reaching defs, no control dependence,
        and a ``switch`` would disconnect the CFG entirely. Joern gives every
        condition expression a CFG node; we promote the expression root."""
        if cfrag[0]:
            return cfrag
        return [croot], [croot]

    # -- statements ------------------------------------------------------
    def stmt(self, n, parent: int, order: int) -> Frag:
        """Lower a statement; returns its CFG fragment."""
        if n is None:
            return EMPTY
        line = self.line(n)

        if isinstance(n, c_ast.Compound):
            block = self.add_node("BLOCK", code="", line=line, order=order)
            self.ast_edge(parent, block)
            self.scope.append({})
            frag = self.seq(*[
                self.stmt(item, block, k)
                for k, item in enumerate(n.block_items or [], 1)
            ])
            self.scope.pop()
            return frag

        if isinstance(n, c_ast.DeclList):
            # for-init declarations: `for (int i = 0, j = n; ...)`
            return self.seq(*[self.stmt(d, parent, k) for k, d in enumerate(n.decls, 1)])

        if isinstance(n, c_ast.Decl):
            t = _render_type(n.type) if n.type is not None else "ANY"
            self.scope[-1][n.name] = t
            local = self.add_node("LOCAL", name=n.name or "", code=f"{t} {n.name}",
                                  line=line, order=order, type_full_name=t)
            self.ast_edge(parent, local)
            if n.init is not None:
                # int x = e  ≡  LOCAL + `x = e` assignment call (Joern shape)
                call = self.add_node("CALL", name="<operator>.assignment",
                                     code=f"{n.name} = {_code_of(n.init)}",
                                     line=line, order=order)
                self.ast_edge(parent, call)
                lhs = self.add_node("IDENTIFIER", name=n.name, code=n.name,
                                    line=line, order=1, type_full_name=t)
                self.ast_edge(call, lhs)
                self.arg_edge(call, lhs)
                rhs, frag = self.expr(n.init, order=2)
                self.ast_edge(call, rhs)
                self.arg_edge(call, rhs)
                return self.seq(frag, ([call], [call]))
            return EMPTY

        if isinstance(n, (c_ast.Assignment, c_ast.UnaryOp, c_ast.FuncCall,
                          c_ast.BinaryOp, c_ast.Cast, c_ast.TernaryOp,
                          c_ast.ExprList, c_ast.ID, c_ast.Constant,
                          c_ast.StructRef, c_ast.ArrayRef)):
            root, frag = self.expr(n, order=order)
            self.ast_edge(parent, root)
            return frag

        if isinstance(n, c_ast.If):
            cs = self.add_node("CONTROL_STRUCTURE", name="IF",
                               code=f"if ({_code_of(n.cond)})", line=line, order=order)
            self.ast_edge(parent, cs)
            croot, cfrag = self.expr(n.cond, order=1)
            self.ast_edge(cs, croot)
            self.edges.append((cs, croot, "CONDITION"))
            cfrag = self.cond_frag(croot, cfrag)
            tfrag = self.stmt(n.iftrue, cs, 2)
            ffrag = self.stmt(n.iffalse, cs, 3) if n.iffalse else EMPTY
            exits: list[int] = []
            for e, x in (tfrag, ffrag):
                if e:
                    self.wire(cfrag[1], e)
                    exits += x
                else:
                    exits += cfrag[1]  # fallthrough arm
            return cfrag[0], list(dict.fromkeys(exits))

        if isinstance(n, c_ast.While):
            cs = self.add_node("CONTROL_STRUCTURE", name="WHILE",
                               code=f"while ({_code_of(n.cond)})", line=line, order=order)
            self.ast_edge(parent, cs)
            croot, cfrag = self.expr(n.cond, order=1)
            self.ast_edge(cs, croot)
            self.edges.append((cs, croot, "CONDITION"))
            cfrag = self.cond_frag(croot, cfrag)
            self._breaks.append([])
            self._continues.append([])
            bfrag = self.stmt(n.stmt, cs, 2)
            brk, cont = self._breaks.pop(), self._continues.pop()
            self.wire(cfrag[1], bfrag[0] or cfrag[0])
            self.wire(bfrag[1] + cont, cfrag[0])
            return cfrag[0], cfrag[1] + brk

        if isinstance(n, c_ast.DoWhile):
            cs = self.add_node("CONTROL_STRUCTURE", name="DO",
                               code=f"do ... while ({_code_of(n.cond)})", line=line, order=order)
            self.ast_edge(parent, cs)
            self._breaks.append([])
            self._continues.append([])
            bfrag = self.stmt(n.stmt, cs, 1)
            brk, cont = self._breaks.pop(), self._continues.pop()
            croot, cfrag = self.expr(n.cond, order=2)
            self.ast_edge(cs, croot)
            self.edges.append((cs, croot, "CONDITION"))
            cfrag = self.cond_frag(croot, cfrag)
            self.wire(bfrag[1] + cont, cfrag[0])
            self.wire(cfrag[1], bfrag[0] or cfrag[0])
            entries = bfrag[0] or cfrag[0]
            return entries, cfrag[1] + brk

        if isinstance(n, c_ast.For):
            cs = self.add_node("CONTROL_STRUCTURE", name="FOR", code="for (...)",
                               line=line, order=order)
            self.ast_edge(parent, cs)
            self.scope.append({})
            ifrag = self.stmt(n.init, cs, 1) if n.init is not None else EMPTY
            if n.cond is not None:
                croot, cfrag = self.expr(n.cond, order=2)
                self.ast_edge(cs, croot)
                self.edges.append((cs, croot, "CONDITION"))
                cfrag = self.cond_frag(croot, cfrag)
            else:
                cfrag = EMPTY
            self._breaks.append([])
            self._continues.append([])
            bfrag = self.stmt(n.stmt, cs, 4)
            brk, cont = self._breaks.pop(), self._continues.pop()
            if n.next is not None:
                nroot, nfrag = self.expr(n.next, order=3)
                self.ast_edge(cs, nroot)
            else:
                nfrag = EMPTY
            self.scope.pop()

            # init -> cond -> body -> next -> cond ; cond -> after ; break -> after
            head = cfrag[0] or bfrag[0] or nfrag[0]
            self.wire(ifrag[1], head)
            if cfrag[0]:
                self.wire(cfrag[1], bfrag[0] or nfrag[0] or cfrag[0])
            self.wire(bfrag[1] + cont, nfrag[0] or head)
            if nfrag[0]:
                self.wire(nfrag[1], head)
            entries = ifrag[0] or head
            return entries, cfrag[1] + brk

        if isinstance(n, c_ast.Return):
            ret = self.add_node("RETURN", code=f"return {_code_of(n.expr)};".replace(" ;", ";"),
                                line=line, order=order)
            self.ast_edge(parent, ret)
            frag = EMPTY
            if n.expr is not None:
                eroot, frag = self.expr(n.expr, order=1)
                self.ast_edge(ret, eroot)
                self.arg_edge(ret, eroot)
            entries, _ = self.seq(frag, ([ret], [ret]))
            assert self.method_return is not None
            self.cfg_edge(ret, self.method_return)
            return entries, []  # no fallthrough

        if isinstance(n, c_ast.Break):
            node = self.add_node("CONTROL_STRUCTURE", name="BREAK", code="break;",
                                 line=line, order=order)
            self.ast_edge(parent, node)
            if self._breaks:
                self._breaks[-1].append(node)
            return [node], []

        if isinstance(n, c_ast.Continue):
            node = self.add_node("CONTROL_STRUCTURE", name="CONTINUE", code="continue;",
                                 line=line, order=order)
            self.ast_edge(parent, node)
            if self._continues:
                self._continues[-1].append(node)
            return [node], []

        if isinstance(n, c_ast.Switch):
            cs = self.add_node("CONTROL_STRUCTURE", name="SWITCH",
                               code=f"switch ({_code_of(n.cond)})", line=line, order=order)
            self.ast_edge(parent, cs)
            croot, cfrag = self.expr(n.cond, order=1)
            self.ast_edge(cs, croot)
            self.edges.append((cs, croot, "CONDITION"))
            cfrag = self.cond_frag(croot, cfrag)
            self._breaks.append([])
            prev_out: list[int] = []
            has_default = False
            items = n.stmt.block_items if isinstance(n.stmt, c_ast.Compound) else [n.stmt]
            for k, item in enumerate(items or [], 1):
                body = item.stmts if isinstance(item, (c_ast.Case, c_ast.Default)) else [item]
                if isinstance(item, c_ast.Default):
                    has_default = True
                case_frag = self.seq(*[
                    self.stmt(s, cs, k * 100 + j) for j, s in enumerate(body or [], 1)
                ])
                if case_frag[0]:
                    self.wire(prev_out, case_frag[0])  # fallthrough
                    if cfrag[1]:
                        self.wire(cfrag[1], case_frag[0])  # dispatch
                    prev_out = case_frag[1]
                # transparent case: fallthrough continues with prev_out
            brk = self._breaks.pop()
            exits = brk + prev_out
            if cfrag[1] and not has_default:
                exits = exits + cfrag[1]
            return cfrag[0], list(dict.fromkeys(exits))

        if isinstance(n, c_ast.Label):
            frag = self.stmt(n.stmt, parent, order)
            if not frag[0]:
                # label on a transparent statement (`done: ;`): materialise a
                # jump target so gotos have somewhere to land
                node = self.add_node("JUMP_TARGET", name=n.name, code=f"{n.name}:",
                                     line=line, order=order)
                self.ast_edge(parent, node)
                frag = ([node], [node])
            self._labels[n.name] = frag[0][0]
            return frag

        if isinstance(n, c_ast.Goto):
            node = self.add_node("CONTROL_STRUCTURE", name="GOTO", code=f"goto {n.name};",
                                 line=line, order=order)
            self.ast_edge(parent, node)
            self._gotos.append((node, n.name))
            return [node], []

        if isinstance(n, c_ast.EmptyStatement):
            return EMPTY

        # unhandled statement kind: opaque node, keep the chain connected
        node = self.add_node("UNKNOWN", code=type(n).__name__, line=line, order=order)
        self.ast_edge(parent, node)
        return [node], [node]

    # -- function --------------------------------------------------------
    def build(self, fdef: c_ast.FuncDef) -> None:
        decl = fdef.decl
        ftype = decl.type  # FuncDecl
        fname = decl.name
        line = self.line(fdef)
        ret_t = _render_type(ftype.type)
        method = self.add_node("METHOD", name=fname, code=_code_of(decl) or fname,
                               line=line, type_full_name=ret_t)
        self.method_return = self.add_node("METHOD_RETURN", code="RET", line=line,
                                           type_full_name=ret_t)
        self.ast_edge(method, self.method_return)

        params = ftype.args.params if ftype.args else []
        self.scope.append({})
        for k, p in enumerate(params, 1):
            if isinstance(p, c_ast.Decl):
                t = _render_type(p.type)
                self.scope[-1][p.name] = t
                pn = self.add_node("METHOD_PARAMETER_IN", name=p.name or "",
                                   code=f"{t} {p.name}", line=self.line(p), order=k,
                                   type_full_name=t)
                self.ast_edge(method, pn)

        entries, exits = self.stmt(fdef.body, method, 1)
        self.scope.pop()
        self.wire([method], entries or [self.method_return])
        self.wire(exits, [self.method_return])
        for node, label in self._gotos:
            if label in self._labels:
                self.cfg_edge(node, self._labels[label])
        self._gotos.clear()
        self._labels.clear()


def parse_functions(code: str) -> list[tuple[str, CPG]]:
    """Parse C source into one ``(function name, CPG)`` pair PER function —
    the `predict` scan surface scores and reports each function separately
    (the reference corpus is one function per row, ``datasets.py:159-198``;
    a raw file is not). Each function gets a fresh builder (own
    scopes/labels); node ids are disjoint across functions."""
    ast, n_typedefs = _parse_with_recovery(_preprocess(code))
    out: list[tuple[str, CPG]] = []
    next_id = 1000100
    for ext in ast.ext:
        if isinstance(ext, c_ast.FuncDef):
            builder = _Builder(line_offset=n_typedefs, next_id=next_id)
            builder.build(ext)
            name = getattr(ext.decl, "name", None) or f"func_{len(out)}"
            out.append((name, CPG(builder.nodes, builder.edges)))
            next_id = builder._next + 100
    if not out:
        raise FrontendError("no function definition found")
    return out


def parse_source(code: str) -> CPG:
    """Parse C source (possibly several functions) into one CPG — the merge
    of :func:`parse_functions` (ONE parsing loop; file-mode and
    per-function-mode must never diverge)."""
    all_nodes: list[Node] = []
    all_edges: list[tuple[int, int, str]] = []
    for _name, cpg in parse_functions(code):
        all_nodes.extend(cpg.nodes.values())
        all_edges.extend(cpg.edges)
    return CPG(all_nodes, all_edges)


def parse_function(code: str) -> CPG:
    """Parse a single C function (the per-function extraction contract the
    reference used with Joern: one ``{id}.c`` file per Big-Vul function)."""
    return parse_source(code)
