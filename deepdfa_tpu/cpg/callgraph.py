"""Call graph over the Joern-schema CPG — the interprocedural layer's index.

The frontend (``cpg/frontend.py``) emits direct function calls as ``CALL``
nodes whose ``name`` is the callee expression's source text, and function
definitions as ``METHOD`` nodes whose ``name`` is the function name (the
native schema carries no ``methodFullName`` column, so name identity IS the
resolution key — same textual-identity convention as the variable model in
``cpg/analyses.py``). :func:`build_callgraph` resolves every non-operator
``CALL`` against the METHODs present in the same (merged) CPG:

- resolved  → a :class:`CallSite` with ``callee`` set, plus a
  ``(caller_method, callee_method)`` edge;
- unresolved (library calls like ``memcpy``, function pointers like
  ``(*fp)(x)``, or malformed empty names) → a *summarized external*: the
  call site is recorded with ``callee=None`` and contributes no transfer
  function — the supergraph treats it as an intraprocedural no-op, exactly
  the per-function semantics the PR 1 analyses already have.

Degradation is total: nothing here raises on dangling or malformed callee
references — those become :mod:`deepdfa_tpu.cpg.validate` diagnostic rows
(``call-ref`` checks), and construction silently falls back to the external
summary. Ambiguous names (two METHODs sharing one name in a merged repo
CPG) resolve to the lowest METHOD id, deterministically.
"""

from __future__ import annotations

import dataclasses

from deepdfa_tpu.cpg.schema import CPG

__all__ = ["CallSite", "CallGraph", "build_callgraph", "method_owner_map"]


@dataclasses.dataclass(frozen=True)
class CallSite:
    """One CALL node: ``callee`` is the resolved METHOD id or None for a
    summarized external."""

    call: int
    caller: int | None
    callee: int | None
    name: str


@dataclasses.dataclass
class CallGraph:
    """``methods``: name → METHOD id (lowest id wins on duplicates);
    ``sites``: every non-operator CALL, resolved or not; ``edges``: the
    resolved (caller, callee) METHOD pairs; ``external``: unresolved callee
    name → call-site count; ``ambiguous``: method names defined more than
    once in the CPG."""

    methods: dict[str, int]
    sites: list[CallSite]
    edges: set[tuple[int, int]]
    external: dict[str, int]
    ambiguous: tuple[str, ...]

    @property
    def n_call_edges(self) -> int:
        return sum(1 for s in self.sites if s.callee is not None)

    def callers_of(self, method: int) -> set[int]:
        return {c for c, t in self.edges if t == method}

    def root_methods(self) -> set[int]:
        """METHODs with no resolved incoming call edge — the entry points
        whose parameters the interprocedural taint seeds (a non-root's
        params are bound at its call sites instead)."""
        targets = {t for _, t in self.edges}
        return set(self.methods.values()) - targets


def method_owner_map(cpg: CPG) -> dict[int, int]:
    """node id → owning METHOD id (the METHOD itself maps to itself).

    Ownership is AST reachability from the METHOD root; nodes outside every
    method body (none in frontend-emitted graphs) are simply absent.
    """
    owner: dict[int, int] = {}
    for n in cpg.nodes.values():
        if n.label != "METHOD":
            continue
        owner[n.id] = n.id
        for d in cpg.ast_descendants(n.id):
            owner[d] = n.id
    return owner


def _is_operator(name: str) -> bool:
    return name.startswith("<operator")


def build_callgraph(cpg: CPG, owner: dict[int, int] | None = None) -> CallGraph:
    """Derive the call graph; never raises on malformed callee references.

    A CALL with an empty/operator name, a name that matches no METHOD, or a
    caller that cannot be attributed (dangling AST) degrades to an external
    summary / ``caller=None`` site rather than an error — the validate
    contract (``call-ref`` checks) reports those rows, construction keeps
    going.
    """
    if owner is None:
        owner = method_owner_map(cpg)
    methods: dict[str, int] = {}
    seen_names: dict[str, int] = {}
    for n in sorted(cpg.nodes.values(), key=lambda x: x.id):
        if n.label != "METHOD" or not n.name:
            continue
        seen_names[n.name] = seen_names.get(n.name, 0) + 1
        methods.setdefault(n.name, n.id)
    ambiguous = tuple(sorted(k for k, c in seen_names.items() if c > 1))

    sites: list[CallSite] = []
    edges: set[tuple[int, int]] = set()
    external: dict[str, int] = {}
    for n in sorted(cpg.nodes.values(), key=lambda x: x.id):
        if n.label != "CALL" or _is_operator(n.name):
            continue
        caller = owner.get(n.id)
        callee = methods.get(n.name) if n.name else None
        if callee == caller and callee is not None:
            pass  # recursion: a real call edge, keep it
        if callee is None:
            external[n.name or "<empty>"] = external.get(n.name or "<empty>", 0) + 1
        elif caller is not None:
            edges.add((caller, callee))
        sites.append(CallSite(call=n.id, caller=caller, callee=callee, name=n.name))
    return CallGraph(methods=methods, sites=sites, edges=edges,
                     external=external, ambiguous=ambiguous)
