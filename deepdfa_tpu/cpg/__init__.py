"""Code-property-graph toolchain (host-side, offline).

The reference drives Joern (Scala/JVM, pinned v1.1.107) for CPG extraction and
reaching-definitions solving (``DDFA/storage/external/*.sc``,
``sastvd/helpers/joern*.py``). This package keeps the **Joern JSON contract**
as an ingestion path (:mod:`deepdfa_tpu.cpg.joern`) but owns the analysis
natively:

- :mod:`deepdfa_tpu.cpg.schema`   — columnar CPG container.
- :mod:`deepdfa_tpu.cpg.joern`    — ``.nodes.json``/``.edges.json``/
  ``.dataflow.json`` readers + an offline Joern runner (gated on a local
  joern install).
- :mod:`deepdfa_tpu.cpg.frontend` — **native C frontend** (pycparser): builds
  Joern-compatible CPGs (AST/CFG/ARGUMENT edges, ``<operator>.*`` call
  naming) with no JVM, so the pipeline is hermetic.
- :mod:`deepdfa_tpu.cpg.dataflow` — reaching-definitions solvers: reference-
  semantics Python worklist, a NumPy bit-vector fast path, and a C++ worklist
  solver (``native/dfa_solver.cpp``) via ctypes.
"""

from deepdfa_tpu.cpg.schema import CPG  # noqa: F401
