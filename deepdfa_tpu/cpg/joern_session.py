"""Interactive Joern session driver.

Drives a long-lived ``joern`` REPL for batch CPG extraction — one JVM spin-up
amortised over many functions instead of one ``joern --script`` invocation
each (the reference drives the same REPL protocol with pexpect,
``DDFA/sastvd/helpers/joern_session.py:33-121``; re-designed here on the
stdlib: subprocess pipes + a reader thread, prompt-synchronised commands,
ANSI stripping, typed parameter marshalling, per-worker workspaces).

Hermetic by construction: nothing here imports Joern artifacts — if the
``joern`` binary is absent, :class:`JoernSession` raises at spawn and the
caller falls back to the native frontend (:mod:`deepdfa_tpu.cpg.frontend`).
"""

from __future__ import annotations

import re
import shutil
import subprocess
import threading
import time
from pathlib import Path

from deepdfa_tpu.resilience import faults

__all__ = [
    "JoernSession",
    "JoernTimeout",
    "strip_ansi",
    "marshal_params",
    "joern_available",
]

_ANSI_RE = re.compile(
    r"\x1b(?:[@-Z\\-_]|\[[0-?]*[ -/]*[@-~])"  # 7-bit C1: ESC + CSI sequences
)

PROMPT = "joern>"
SCRIPT_DIR = Path(__file__).parent / "queries"


def strip_ansi(text: str) -> str:
    """Remove ANSI escape sequences (the REPL colors its prompt even under
    ``--nocolors`` on some terminals)."""
    return _ANSI_RE.sub("", text)


class JoernTimeout(TimeoutError):
    """No prompt within the deadline. ``partial`` carries the full
    ANSI-stripped buffer accumulated so far (the message keeps only the
    tail) — the extraction supervisor logs it so quarantine entries say
    *why* a function hung, not just that it did."""

    def __init__(self, message: str, partial: str = ""):
        super().__init__(message)
        self.partial = partial


def _scala_str(val: str | Path) -> str:
    """A quoted Scala string literal with escaping — paths can contain
    quotes/backslashes and must not break out of the literal."""
    escaped = str(val).replace("\\", "\\\\").replace('"', '\\"')
    return f'"{escaped}"'


def marshal_params(params: dict) -> str:
    """Render ``exec(...)`` arguments with Scala literal syntax: strings and
    paths quoted (WITH escaping — file paths can contain quotes), bools
    lowercased, ints/floats bare."""
    parts = []
    for key, val in params.items():
        if isinstance(val, bool):
            rendered = str(val).lower()
        elif isinstance(val, (int, float)):
            rendered = str(val)
        elif isinstance(val, (str, Path)):
            rendered = _scala_str(val)
        else:
            raise TypeError(f"cannot marshal {key}={val!r} ({type(val).__name__})")
        parts.append(f"{key}={rendered}")
    return ", ".join(parts)


def joern_available(joern_bin: str = "joern") -> bool:
    return shutil.which(joern_bin) is not None


class JoernSession:
    """One interactive ``joern`` REPL.

    ``worker_id > 0`` switches into a private ``workers/{id}`` workspace so
    parallel sessions don't clobber each other's projects (the reference's
    per-worker workspace scheme)."""

    def __init__(
        self,
        worker_id: int = 0,
        joern_bin: str = "joern",
        cwd: str | Path | None = None,
        timeout: float = 600.0,
        clean: bool = False,
    ):
        if not joern_available(joern_bin):
            raise RuntimeError(
                f"joern binary {joern_bin!r} not on PATH — use the native "
                "frontend (deepdfa_tpu.cpg.frontend) instead"
            )
        self.timeout = timeout
        self.cwd = Path(cwd) if cwd is not None else Path.cwd()
        workspace = "workspace" if worker_id == 0 else f"workers/{worker_id}"
        if clean:  # must happen BEFORE the REPL starts and switches into it
            ws = self.cwd / workspace
            if ws.exists():
                shutil.rmtree(ws)
        self.proc = subprocess.Popen(
            [joern_bin, "--nocolors"],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            cwd=self.cwd,
            text=True,
            bufsize=0,
        )
        self._buf: list[str] = []
        self._cond = threading.Condition()
        self._eof = False
        self._reader = threading.Thread(target=self._pump, daemon=True)
        self._reader.start()
        self.read_until_prompt()
        if worker_id != 0:
            self.switch_workspace(workspace)

    # -- low-level protocol -------------------------------------------------
    def _pump(self) -> None:
        try:
            while True:
                chunk = self.proc.stdout.read(1)
                if not chunk:
                    break
                with self._cond:
                    self._buf.append(chunk)
                    self._cond.notify_all()
        finally:
            with self._cond:
                self._eof = True
                self._cond.notify_all()

    def read_until_prompt(self, timeout: float | None = None) -> str:
        """Block until the REPL prints its prompt; return (and consume) the
        output before it, ANSI-stripped."""
        deadline = time.monotonic() + (timeout if timeout is not None else self.timeout)
        with self._cond:
            while True:
                text = "".join(self._buf)
                idx = text.find(PROMPT)
                if idx >= 0:
                    del self._buf[:]
                    rest = text[idx + len(PROMPT):]
                    if rest:
                        self._buf.append(rest)
                    return strip_ansi(text[:idx]).replace("\r", "").strip()
                if self._eof:
                    raise RuntimeError(
                        "joern REPL exited unexpectedly:\n" + strip_ansi(text)
                    )
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    buffered = strip_ansi(text)
                    raise JoernTimeout(
                        f"no joern prompt within {timeout or self.timeout}s; "
                        f"buffered: {buffered[-500:]!r}",
                        partial=buffered,
                    )
                self._cond.wait(min(remaining, 1.0))

    def run_command(self, command: str, timeout: float | None = None) -> str:
        # chaos points: a JVM that dies under a command, and one that eats
        # the command whole (no output, no prompt → timeout path)
        if faults.fire("joern.die"):
            self.proc.kill()
        elif not faults.fire("joern.hang"):
            self.proc.stdin.write(command + "\n")
            self.proc.stdin.flush()
        return self.read_until_prompt(timeout=timeout)

    # -- joern commands -----------------------------------------------------
    def run_script(
        self,
        script: str,
        params: dict,
        script_dir: str | Path = SCRIPT_DIR,
        timeout: float | None = None,
    ) -> str:
        """Import ``{script}.sc`` from ``script_dir`` and call its ``exec``
        entry point with marshalled parameters.

        Ammonite ``$file`` imports are cwd-relative and dotted, so scripts
        outside the session cwd are staged into ``deepdfa_joern_scripts/``
        first. Every path segment must be a valid Scala identifier — a
        dotted/hidden directory name would render as ``import $file..foo``
        and fail to parse (the reference's ``storage.external`` import obeys
        the same constraint, ``joern_session.py:81-86``).
        """
        src = Path(script_dir) / f"{script}.sc"
        if not src.exists():
            raise FileNotFoundError(src)
        try:
            rel = src.resolve().relative_to(self.cwd.resolve())
        except ValueError:
            stage = self.cwd / "deepdfa_joern_scripts"
            stage.mkdir(exist_ok=True)
            shutil.copyfile(src, stage / src.name)
            rel = Path("deepdfa_joern_scripts") / src.name
        dotted = ".".join(rel.with_suffix("").parts)
        if not all(re.fullmatch(r"[A-Za-z_][A-Za-z0-9_]*", p)
                   for p in rel.with_suffix("").parts):
            raise ValueError(
                f"script path {rel} has segments that are not valid Scala "
                "identifiers — Ammonite $file imports cannot express it"
            )
        self.run_command(f"import $file.{dotted}")
        return self.run_command(
            f"{script}.exec({marshal_params(params)})", timeout=timeout
        )

    def switch_workspace(self, path: str) -> str:
        return self.run_command(f"switchWorkspace({_scala_str(path)})")

    def import_code(self, filepath: str | Path) -> str:
        return self.run_command(f"importCode({_scala_str(filepath)})")

    def import_cpg(self, filepath: str | Path) -> str:
        """Prefer the saved ``.cpg.bin`` next to the file; fall back to
        importing the source and saving the binary for next time."""
        bin_path = Path(str(filepath) + ".cpg.bin")
        if bin_path.exists():
            return self.run_command(f"importCpg({_scala_str(bin_path)})")
        out = self.import_code(filepath)
        try:
            shutil.copyfile(self.cpg_path(), bin_path)
        except OSError:
            pass
        return out

    def delete_project(self) -> str:
        return self.run_command("delete")

    def list_workspace(self) -> str:
        return self.run_command("workspace")

    def cpg_path(self) -> Path:
        project_path = self.run_command("print(project.path)")
        return Path(project_path.strip().splitlines()[-1]) / "cpg.bin"

    # -- lifecycle ----------------------------------------------------------
    def close(self) -> None:
        try:
            self.proc.stdin.write("exit\n")
            self.proc.stdin.flush()
            self.proc.stdin.write("y\n")
            self.proc.stdin.flush()
            self.proc.wait(timeout=10)
        except (OSError, subprocess.TimeoutExpired):
            self.proc.kill()
            self.proc.wait(timeout=10)

    def __enter__(self) -> "JoernSession":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
