// Generic monotone gen/kill dataflow worklist solver over a CSR-encoded CFG.
//
// Native throughput path for corpus preprocessing: the reference ran its
// reaching-defs fixpoint inside Joern's JVM (DataFlowSolver /
// ReachingDefProblem, invoked from DDFA/storage/external/get_func_graph.sc)
// and kept a Python reference implementation
// (DDFA/code_gnn/analysis/dataflow.py:155-177). This solver generalises the
// same MOP semantics to any gen/kill instance:
//
//   in[n]  = MEET over preds p of out[p]   (may: OR, must: AND)
//   out[n] = gen[n] | (in[n] & ~kill[n])
//
// chaotic iteration until fixpoint. Direction is the caller's concern: a
// backward analysis passes the reversed CFG (pred/succ swapped) and re-labels
// the outputs (see cpg/analyses.py). For must-meet the caller initialises
// out_out to all-ones (TOP); boundary nodes (no preds) always get in = 0.
// Facts are bit positions in 64-bit word vectors; callers pack/unpack.
//
// Exposed via ctypes; no Python.h dependency.

#include <cstdint>
#include <cstring>
#include <vector>

extern "C" int solve_dataflow(
    int32_t n_nodes, int32_t n_facts, int32_t meet_is_must,
    const int32_t* pred_indptr, const int32_t* pred_indices,
    const int32_t* succ_indptr, const int32_t* succ_indices,
    const uint64_t* gen, const uint64_t* kill,
    uint64_t* in_out, uint64_t* out_out) {
  if (n_nodes < 0 || n_facts < 0) return 1;
  if (n_nodes == 0) return 0;
  const int32_t words = n_facts > 0 ? (n_facts + 63) / 64 : 1;

  std::vector<uint64_t> scratch(words);
  std::vector<int32_t> work;
  std::vector<char> in_work(n_nodes, 1);
  work.reserve(n_nodes);
  for (int32_t i = 0; i < n_nodes; ++i) work.push_back(i);

  while (!work.empty()) {
    const int32_t n = work.back();
    work.pop_back();
    in_work[n] = 0;

    uint64_t* in_n = in_out + static_cast<size_t>(n) * words;
    const int32_t p_begin = pred_indptr[n], p_end = pred_indptr[n + 1];
    if (meet_is_must && p_begin != p_end) {
      std::memset(in_n, 0xFF, sizeof(uint64_t) * words);
      for (int32_t e = p_begin; e < p_end; ++e) {
        const uint64_t* out_p = out_out + static_cast<size_t>(pred_indices[e]) * words;
        for (int32_t w = 0; w < words; ++w) in_n[w] &= out_p[w];
      }
    } else {
      // may-meet union; must-meet boundary (no preds) is pinned to 0
      std::memset(in_n, 0, sizeof(uint64_t) * words);
      for (int32_t e = p_begin; e < p_end; ++e) {
        const uint64_t* out_p = out_out + static_cast<size_t>(pred_indices[e]) * words;
        for (int32_t w = 0; w < words; ++w) in_n[w] |= out_p[w];
      }
    }

    const uint64_t* gen_n = gen + static_cast<size_t>(n) * words;
    const uint64_t* kill_n = kill + static_cast<size_t>(n) * words;
    uint64_t* out_n = out_out + static_cast<size_t>(n) * words;
    bool changed = false;
    for (int32_t w = 0; w < words; ++w) {
      const uint64_t v = gen_n[w] | (in_n[w] & ~kill_n[w]);
      if (v != out_n[w]) changed = true;
      scratch[w] = v;
    }
    if (changed) {
      std::memcpy(out_n, scratch.data(), sizeof(uint64_t) * words);
      for (int32_t e = succ_indptr[n]; e < succ_indptr[n + 1]; ++e) {
        const int32_t s = succ_indices[e];
        if (!in_work[s]) {
          in_work[s] = 1;
          work.push_back(s);
        }
      }
    }
  }
  return 0;
}

// Historical entry point: reaching definitions is forward-may with in/out
// buffers zero-initialised by the caller.
extern "C" int solve_reaching_defs(
    int32_t n_nodes, int32_t n_defs,
    const int32_t* pred_indptr, const int32_t* pred_indices,
    const int32_t* succ_indptr, const int32_t* succ_indices,
    const uint64_t* gen, const uint64_t* kill,
    uint64_t* in_out, uint64_t* out_out) {
  return solve_dataflow(n_nodes, n_defs, 0, pred_indptr, pred_indices,
                        succ_indptr, succ_indices, gen, kill, in_out, out_out);
}
