// Reaching-definitions worklist solver over a CSR-encoded CFG.
//
// Native throughput path for corpus preprocessing: the reference ran this
// fixpoint inside Joern's JVM (DataFlowSolver / ReachingDefProblem, invoked
// from DDFA/storage/external/get_func_graph.sc) and kept a Python reference
// implementation (DDFA/code_gnn/analysis/dataflow.py:155-177). Same MOP
// semantics here: in[n] = U out[p], out[n] = gen[n] | (in[n] & ~kill[n]),
// chaotic iteration until fixpoint. Definitions are bit positions in
// 64-bit word vectors; callers pack/unpack (see cpg/dataflow.py).
//
// Exposed via ctypes; no Python.h dependency.

#include <cstdint>
#include <cstring>
#include <vector>

extern "C" int solve_reaching_defs(
    int32_t n_nodes, int32_t n_defs,
    const int32_t* pred_indptr, const int32_t* pred_indices,
    const int32_t* succ_indptr, const int32_t* succ_indices,
    const uint64_t* gen, const uint64_t* kill,
    uint64_t* in_out, uint64_t* out_out) {
  if (n_nodes < 0 || n_defs < 0) return 1;
  if (n_nodes == 0) return 0;
  const int32_t words = n_defs > 0 ? (n_defs + 63) / 64 : 1;

  std::vector<uint64_t> scratch(words);
  std::vector<int32_t> work;
  std::vector<char> in_work(n_nodes, 1);
  work.reserve(n_nodes);
  for (int32_t i = 0; i < n_nodes; ++i) work.push_back(i);

  while (!work.empty()) {
    const int32_t n = work.back();
    work.pop_back();
    in_work[n] = 0;

    uint64_t* in_n = in_out + static_cast<size_t>(n) * words;
    std::memset(in_n, 0, sizeof(uint64_t) * words);
    for (int32_t e = pred_indptr[n]; e < pred_indptr[n + 1]; ++e) {
      const uint64_t* out_p = out_out + static_cast<size_t>(pred_indices[e]) * words;
      for (int32_t w = 0; w < words; ++w) in_n[w] |= out_p[w];
    }

    const uint64_t* gen_n = gen + static_cast<size_t>(n) * words;
    const uint64_t* kill_n = kill + static_cast<size_t>(n) * words;
    uint64_t* out_n = out_out + static_cast<size_t>(n) * words;
    bool changed = false;
    for (int32_t w = 0; w < words; ++w) {
      const uint64_t v = gen_n[w] | (in_n[w] & ~kill_n[w]);
      if (v != out_n[w]) changed = true;
      scratch[w] = v;
    }
    if (changed) {
      std::memcpy(out_n, scratch.data(), sizeof(uint64_t) * words);
      for (int32_t e = succ_indptr[n]; e < succ_indptr[n + 1]; ++e) {
        const int32_t s = succ_indices[e];
        if (!in_work[s]) {
          in_work[s] = 1;
          work.push_back(s);
        }
      }
    }
  }
  return 0;
}
